"""Per-process runtime: the CoreWorker equivalent.

Embedded in every driver and worker process (reference:
`src/ray/core_worker/core_worker.h:295`).  Owns:

- the io thread running the asyncio control plane (connections to the
  local node daemon, the controller, and leased/peer workers),
- the in-process store for small/direct-return objects (reference:
  `store_provider/memory_store/`) and the node's shm store client,
- the reference counter (owner-side local/submitted/borrower counts —
  reference: `reference_count.h:64`),
- the task manager (pending tasks, retries, lineage for reconstruction —
  reference: `task_manager.h:208`); the completion state machine lives
  in `core/completion.py`,
- the SHARDED lease-based submitter (`core/owner_shard.py`): workers
  are leased from the node daemon (batched grants), then tasks are
  pushed DIRECTLY to the leased worker over its socket, pipelined,
  bypassing the daemon on the hot path (reference two-level scheduling:
  `normal_task_submitter.h:75`, lease pipelining, and `SubmitActorTask`
  direct pushes `actor_task_submitter.h:75`).  With `owner_shards` > 1
  the submission/completion lanes run on N event loops keyed by task
  id (docs/control_plane.md),
- task execution when running as a worker (reference:
  `core_worker.cc:2908` ExecuteTask), with per-caller ordered actor
  queues (`transport/actor_scheduling_queue.h`) and per-tick coalesced
  `task_result_batch` replies (`core/completion.py`).

Submission runs entirely on the calling thread (spec build, state
registration under a lock, frame pickling) and hands the owning
shard's loop only a batched flush — this is what makes >10k tasks/s
feasible in Python.
"""

from __future__ import annotations

import asyncio
import contextvars
import logging
import os
import random
import sys
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeoutError
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.core import completion as _completion
from ray_tpu.core import rpc, serialization as ser
from ray_tpu.core.config import Config, get_config
from ray_tpu.core.owner_shard import (
    PIPELINE_DEPTH,
    OwnerShard,
    shard_index,
)
from ray_tpu.core.ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.retry import RetryBudget, backoff_delay_s
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.core.task_spec import (
    STREAMING,
    ActorCreationSpec,
    ArgRef,
    Resources,
    SchedulingStrategy,
    TaskResult,
    TaskSpec,
    function_id_of,
)
from ray_tpu.shm import ObjectNotFoundError, ShmStore
from ray_tpu.util import sanitizer as _sanitizer

logger = logging.getLogger(__name__)

# `rt memory` callsite column, opt-in like the reference's
# RAY_record_ref_creation_sites (stack capture per ref is too costly to
# leave on by default)
_RECORD_CALLSITES = os.environ.get(
    "RT_RECORD_REF_CREATION_SITES", ""
) not in ("", "0")


import sysconfig as _sysconfig

_STDLIB_PREFIX = _sysconfig.get_paths().get("stdlib", "/nonexistent")
# the installed package directory, NOT a name substring — a user
# checkout whose path merely contains "ray_tpu" must still get
# callsites
_PKG_PREFIX = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _creation_site() -> str:
    """First stack frame outside the ray_tpu package AND the stdlib,
    as 'file:line in fn' — the user frame that created the ref."""
    for f in reversed(traceback.extract_stack(limit=16)[:-2]):
        fn = f.filename or ""
        if not fn.startswith(_PKG_PREFIX) and not fn.startswith(
                _STDLIB_PREFIX):
            return f"{fn}:{f.lineno} in {f.name}"
    return ""

# Ambient end-to-end deadline of the task currently executing in this
# context: a ContextVar (not a thread-local) because async actors
# interleave many tasks on ONE io-loop thread — each asyncio task gets
# its own context copy, so a nested `.remote()` inherits exactly its
# parent's budget and never a concurrent neighbor's.  Pool threads set
# it at task start (overwrite, even to None), so reuse can't leak one.
_ambient_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "rt_ambient_deadline", default=None
)


def remaining_deadline_s():
    """The executing task's remaining end-to-end budget in seconds, or
    None when no deadline is in force.  Read-only view of the ambient
    deadline for code that wants to PROPAGATE the budget into a
    non-task queue (e.g. the serve LLM engine's admission queue, so
    queued requests can be shed once their caller must have given up)
    rather than spawn nested tasks."""
    deadline = _ambient_deadline.get()
    if deadline is None:
        return None
    return max(0.0, deadline - time.monotonic())


def _wake_nudge():
    """No-op callback: waking the selector is the entire point."""


_INLINE = "inline"
_SHM = "shm"
# sentinel for "not resolved by the fast arg-pin pass" (_try_pin_args)
_UNRESOLVED = object()
# pipelining depth lives with the lease machinery now
# (core/owner_shard.py); the alias keeps the exec-pool sizing below
# reading naturally
_PIPELINE_DEPTH = PIPELINE_DEPTH


@dataclass
class _ObjectState:
    """Owner-side record of one owned object."""

    ready: asyncio.Event
    where: Optional[str] = None  # "inline" | "shm"
    value: Optional[bytes] = None  # serialized envelope when inline
    node_id: Optional[str] = None  # location when in shm
    size: int = 0
    error: Optional[bytes] = None  # serialized error envelope
    #: seal-time checksum for the opt-in local-get verifier
    #: (object_integrity_verify_get); None = not recorded
    checksum: Optional[int] = None


@dataclass
class _StreamState:
    """Owner-side record of one streaming-generator task.

    Reference: the streaming-generator refs the TaskManager tracks
    (`src/ray/core_worker/task_manager.h:208` — generator returns are
    dynamically appended as the executor yields).  Items arrive as
    `stream_item` messages ahead of the final `task_result`; each item
    becomes an owned object (inline or shm) addressable by
    `ObjectID.for_return(task_id, index)`.
    """

    event: asyncio.Event
    # yield-index -> item ref: keyed (not appended) so delivery-path
    # switches mid-stream (direct conn -> daemon relay) or retry replays
    # can never reorder consumption — the consumer always takes index
    # consumed+1
    items: Dict[int, "ObjectRef"] = field(default_factory=dict)
    consumed: int = 0
    total: Optional[int] = None  # set by the final ok task_result
    error: Optional[bytes] = None  # error envelope ends the stream
    # set once when the producing task finishes (ok or error) — for
    # completion watchers that must not race the consumer's `event`
    done: asyncio.Event = field(default_factory=asyncio.Event)


@dataclass
class _RefCount:
    local: int = 0
    submitted: int = 0
    borrowers: int = 0
    # Binary pin: 1 while an owned ref sits inside some serialized
    # container (task return / put) that no consumer has registered yet;
    # released by the first borrow registration or local deserialization.
    contained: int = 0
    # In-flight protection for FOREIGN-owned refs this process forwards
    # inside serialized messages (task args / returns): while transit>0
    # the entry survives local drops, so our borrow stays registered at
    # the owner until the receiver has registered ITS borrow — closing
    # the forwarded-ref window of the reference's borrower protocol
    # (`reference_count.h:64` + WaitForRefRemoved; here the receiver's
    # registration is acknowledged before the carrying task's result).
    transit: int = 0
    # True while this process holds a registered borrow at the ref's
    # owner (drives exactly-one add_borrow/remove_borrow per entry
    # lifetime regardless of how local/transit counts interleave).
    registered: bool = False
    # owner address for borrowed entries, so EVERY deletion path
    # (_maybe_free) can send the final remove_borrow
    owner_addr: Optional[tuple] = None
    # owner-side borrower identity ledger: address -> count (reference:
    # the owner tracks WHICH workers borrow, `reference_count.h:64`)
    borrower_addrs: Dict[tuple, int] = field(default_factory=dict)
    # Lineage pins (reference: `reference_count.h` lineage reachability):
    # +1 per DOWNSTREAM return object whose retained lineage names this
    # ref as a task argument.  While > 0 the entry (and its lineage
    # entry, if owned) survives user drops, so reconstructing a lost
    # downstream object can re-derive its inputs — without this, a
    # multi-stage pipeline that drops intermediate refs for memory
    # (the shuffle exchange) loses reconstructability mid-chain.
    # Released when the downstream object's own lineage entry is popped
    # at ITS free (cascading the release up the chain).
    lineage: int = 0
    # creation callsite ("file:line in fn"), recorded only under
    # RT_RECORD_REF_CREATION_SITES=1 (reference:
    # RAY_record_ref_creation_sites + `ray memory` callsite column)
    callsite: str = ""

    def total(self):
        return (self.local + self.submitted + self.borrowers
                + self.contained + self.transit + self.lineage)


@dataclass
class _PendingTask:
    spec: TaskSpec
    retries_left: int
    # (inner_id, owner) pairs: foreign refs serialized into this task's
    # args, transit-pinned until the task's FINAL completion
    transit: List[Tuple[bytes, tuple]] = field(default_factory=list)
    # retries already granted for this task (drives the backoff
    # exponent and the failure message's attempt accounting)
    attempts: int = 0
    # owner-side deadline watchdog (asyncio TimerHandle), cancelled at
    # FINAL completion so the loop doesn't hold a live timer for the
    # full timeout_s of every already-finished call; survives retries
    # (the deadline covers the whole lineage)
    deadline_timer: Optional[object] = None
    # registration instant: basis of the submit->final-completion
    # latency histogram (`rt_owner_task_latency_seconds`); always
    # stamped (one clock read), only OBSERVED when metrics are on
    t_submit: float = field(default_factory=time.monotonic)


# Process-wide per-actor sequence numbers: every caller path (handles,
# lineage reconstruction) draws from the same counter so the executor's
# in-order delivery sees one consistent stream per caller process.
_actor_seq_counters: Dict[Tuple[bytes, Optional[str]], int] = {}
_actor_seq_lock = threading.Lock()


def next_actor_seq(aid: bytes, group: Optional[str] = None) -> int:
    """Per-(actor, concurrency-group) sequence counter: each group is
    its own ordered stream, so a gap in one lane never stalls another
    (reference: per-group scheduling queues in
    `concurrency_group_manager.h`)."""
    with _actor_seq_lock:
        key = (aid, group)
        n = _actor_seq_counters.get(key, 0)
        _actor_seq_counters[key] = n + 1
        return n


class Runtime:
    """One per process; `driver` or `worker` mode."""

    def __init__(self, mode: str):
        self.mode = mode
        self.cfg: Config = get_config()
        self.job_id = JobID.random()
        self.worker_id = WorkerID.random()
        self.node_id: str = ""
        self.loop = asyncio.new_event_loop()
        _sanitizer.register_loop(self.loop, "rt-io", audit_timers=False)
        self._io_thread = threading.Thread(
            target=self._run_loop, name="rt-io", daemon=True
        )
        self.noded: Optional[rpc.Connection] = None
        self.controller: Optional[rpc.Connection] = None
        self.store: Optional[ShmStore] = None
        self.my_socket: Optional[str] = None
        self._server: Optional[rpc.Server] = None

        # owner-side state; _state_lock guards dict mutation from the
        # submitting thread; the io thread holds it in result handlers
        self._state_lock = _sanitizer.wrap_lock(
            threading.RLock(), "runtime._state_lock",
            _sanitizer.RUNTIME_STATE_LOCK,
        )
        self.objects: Dict[bytes, _ObjectState] = {}
        self.refs: Dict[bytes, _RefCount] = {}
        self.pending_tasks: Dict[bytes, _PendingTask] = {}
        self.lineage: Dict[bytes, TaskSpec] = {}  # return id -> creating spec
        self._streams: Dict[bytes, _StreamState] = {}  # task id -> stream

        # lease-based submission is owner-sharded: each shard owns its
        # lease pools, its worker connections, and (shards > 1) its own
        # event loop + node-daemon connection (core/owner_shard.py).
        # Shard 0 with owner_shards == 1 shares this runtime's io loop —
        # the classic single-owner plane.
        self._shards: List[OwnerShard] = []
        # actor submission: direct conns to actor workers
        self._actor_conns: Dict[bytes, rpc.Connection] = {}
        self._actor_queue: Dict[bytes, deque] = {}
        self._actor_assigned: Dict[rpc.Connection, Dict[bytes, TaskSpec]] = {}
        self._actor_connecting: set = set()
        self._actor_addr: Dict[bytes, Tuple[str, str]] = {}

        # function export cache: id(fn) -> (fid, blob, pinned fn)
        self._fn_export: Dict[int, Tuple[bytes, bytes, Any]] = {}
        self._exported_fids: set = set()
        self._fn_cache: Dict[bytes, Any] = {}

        # executor-side state; pool width >= _PIPELINE_DEPTH so pushed
        # tasks always find a thread (see _PIPELINE_DEPTH comment)
        self._exec_pool = ThreadPoolExecutor(
            max_workers=max(8, _PIPELINE_DEPTH), thread_name_prefix="rt-exec"
        )
        self.actor_instance: Any = None
        self.actor_id: Optional[ActorID] = None
        self._actor_aspec: Optional[ActorCreationSpec] = None
        # keyed by (caller_worker_id, concurrency_group): one ordered
        # delivery stream per lane
        self._actor_seq_expect: Dict[tuple, int] = {}
        self._actor_seq_buffer: Dict[tuple, Dict[int, TaskSpec]] = {}
        self._actor_drain_lock: Optional[asyncio.Lock] = None
        # executor-side duplicate-delivery fence: task id -> the
        # serial of the conn it was dispatched from, bounded FIFO (a
        # SERIAL, not id(): a recycled object address must never make
        # a reconnect retry look like a replay).  A stale-seq arrival
        # whose task id is in here ON THE SAME CONNECTION is a
        # transport REPLAY (dropped — its original reply rides the
        # same live stream); the same task id on a NEW connection is a
        # reconnect retry whose original result died with the old
        # conn, and must re-execute — see _exec_actor_ordered.
        self._actor_dispatched: Dict[bytes, int] = {}
        self._actor_dispatched_order: deque = deque()
        # per-(caller, group) gap timers: advance past sequence numbers
        # that never arrive (consumed by a previous actor incarnation)
        self._actor_seq_timers: Dict[tuple, object] = {}
        self._put_counter = 0
        self._task_local = threading.local()
        # parked-operation count behind the blocked-worker protocol
        # (get()/arg-materialize stalls; see _notify_blocked)
        self._blocked_ops = 0
        self._blocked_ops_lock = threading.Lock()
        # shm objects this process has materialized via get: the pin is
        # held for the process lifetime because deserialized numpy/jax
        # values are zero-copy views into the segment (the reference
        # pins plasma buffers the same way while Python buffers exist)
        self._held_pins: set = set()
        # container object id -> borrows/pins it holds on inner refs
        self._contained_in: Dict[bytes, list] = {}
        # object id -> threading.Events set when _maybe_free retires
        # the entry (wait_freed: event-driven lifetime assertions for
        # tests/tools instead of wall-clock contains() polling)
        self._free_waiters: Dict[bytes, list] = {}
        # executor side: task id -> transit pins on foreign refs that
        # rode out in that task's returns (released by transit_release)
        self._return_transit: Dict[bytes, list] = {}
        # owner side: task id -> registration-ack futures for contained
        # borrows arriving in STREAM items (awaited with the final
        # result's acks before transit_release)
        self._stream_reg_acks: Dict[bytes, list] = {}
        # borrow-registration ACKs outstanding in this worker; awaited
        # before any task result is sent (see on_ref_deserialized)
        self._pending_borrow_acks: list = []
        # driver side: recent worker log lines (name, pid, stream, line)
        # received via worker_log — tests and tooling read this; the
        # lines are also echoed to stderr (core/log_stream.py)
        self._worker_log_lines: deque = deque(maxlen=2000)
        # pubsub: channel -> list of local subscriber queues; channels
        # registered with the controller (re-sent after a reconnect)
        self._pubsub_queues: Dict[str, list] = {}
        self._pubsub_registered: set = set()
        # channels whose last (un)subscribe RPC outcome is unknown
        # (timeout / cancelled mid-RPC); resolved by the reconciler
        self._pubsub_uncertain: set = set()
        # single-writer reconciler serializes all (un)subscribe RPCs on
        # the io loop (see _pubsub_reconcile); binds to the loop on
        # first acquisition
        self._pubsub_async_lock = asyncio.Lock()
        # coalesced ref-event channel (reference: `src/ray/pubsub/` —
        # WaitForRefRemoved rides a per-worker-pair channel so borrow
        # traffic is O(#counterparts), not O(#objects)): un-ACK'd
        # add/remove borrow events queue per owner address and flush as
        # ONE routed frame per counterpart per flush window
        self._ref_event_lock = threading.Lock()
        self._ref_event_queues: Dict[tuple, list] = {}
        self._ref_event_flush_scheduled = False
        # bulk-resolved owner replies awaiting their per-ref consumer
        # (io-loop only; see _prime_borrowed)
        self._primed_replies: Dict[bytes, object] = {}
        # executing normal tasks: task_id -> thread ident (cancellation)
        self._task_threads: Dict[bytes, int] = {}
        # runtime-env dedication (worker mode): hash applied, if any
        self._applied_env_hash: Optional[str] = None
        self._shutdown = False
        # retry pacing: one budget per runtime (retries spend, successes
        # refill — core/retry.py) and a seeded jitter rng so chaos tests
        # replay deterministically under a fixed RT_RETRY_JITTER_SEED
        self._retry_budget = RetryBudget(
            cap=self.cfg.task_retry_budget_cap,
            refill=self.cfg.task_retry_budget_refill,
        )
        _seed = os.environ.get("RT_RETRY_JITTER_SEED")
        self._retry_rng = random.Random(int(_seed) if _seed else None)
        # actor-reconnect backoff state: aid -> consecutive dial failures
        self._actor_connect_attempts: Dict[bytes, int] = {}
        from ray_tpu.core.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(
            max_buffer=self.cfg.task_events_buffer_size
        )
        # config can enable core-path metrics without the env flag
        # (init(_system_config={"metrics_enabled": True})); set_enabled
        # mirrors it into the env so spawned children inherit
        if self.cfg.metrics_enabled:
            from ray_tpu.metrics import metric_defs as _md

            _md.set_enabled(True)
        # executor-side completion coalescing (core/completion.py):
        # results for one owner ship as one frame per loop tick
        self._result_coalescer = _completion.ResultCoalescer(self)

    # ------------------------------------------------------------------
    # bootstrap
    # ------------------------------------------------------------------
    def _run_loop(self):
        asyncio.set_event_loop(self.loop)
        # /proc-readable identity for the per-plane CPU accounting
        # (perf.py --owner-shards reports per-shard us/task)
        self._io_native_tid = threading.get_native_id()
        self.loop.run_forever()

    def start(self, node_socket: str, controller_addr: Tuple[str, int],
              serve_dir: Optional[str] = None):
        self._io_thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self._connect(node_socket, controller_addr, serve_dir), self.loop
        )
        fut.result(timeout=self.cfg.rpc_connect_timeout_s)
        # owner shards: drivers honor cfg.owner_shards; workers always
        # run the shared single-shard plane (their nested submissions
        # are a side channel, not the bottleneck)
        n = (max(1, int(self.cfg.owner_shards))
             if self.mode == "driver" else 1)
        self._shards = [OwnerShard(self, i, shared=(n == 1))
                        for i in range(n)]
        for s in self._shards:
            s.start(node_socket)

    def _shard_for(self, task_id_bytes: bytes) -> OwnerShard:
        return self._shards[shard_index(task_id_bytes, len(self._shards))]

    def _find_lease(self, conn):
        """-> (shard, pool, lease) owning `conn`, or None."""
        for shard in self._shards:
            entry = shard.conn_lease.get(conn)
            if entry is not None:
                return (shard, *entry)
        return None

    def owner_shard_stats(self) -> List[Dict]:
        """Per-shard accounting for tests and perf.py: submitted /
        completed / lease + queue depth / CPU seconds per shard."""
        return [s.stats() for s in self._shards]

    def _wake_main_loop(self):
        """Wake this runtime's io loop after an off-thread completion:
        ready-Event waiter callbacks queued with plain `call_soon` from
        a shard/submitter thread never wake a selector sleeping in
        `run_forever` — a `call_soon_threadsafe` no-op writes the
        self-pipe and the loop drains everything queued.  Called by
        completion.complete_task's finally block."""
        if threading.current_thread() is self._io_thread:
            return  # in-loop completion: call_soon already suffices
        try:
            self.loop.call_soon_threadsafe(_wake_nudge)
        except RuntimeError:
            pass  # loop closed mid-teardown

    async def _connect(self, node_socket, controller_addr, serve_dir):
        if serve_dir is not None:
            # workers serve a socket so owners push tasks directly
            self.my_socket = os.path.join(
                serve_dir, f"w_{self.worker_id.hex()[:12]}.sock"
            )
            self._server = rpc.Server(
                self, name=f"worker-{self.worker_id.hex()[:8]}", handler=self._handle
            )
            await self._server.start_unix(self.my_socket)
        self.noded = await rpc.connect_unix(
            node_socket, handler=self._handle, name="noded"
        )
        self._flush_task = asyncio.ensure_future(
            self._flush_task_events_loop()
        )
        self._controller_addr = tuple(controller_addr)
        self.controller = await rpc.connect_tcp(
            *controller_addr, handler=self._handle, name="controller"
        )
        self.controller.on_close = self._on_controller_lost
        info = await self.noded.call(
            "register",
            {
                "kind": self.mode,
                "worker_id": self.worker_id.hex(),
                "pid": os.getpid(),
                "job_id": self.job_id.hex(),
                "socket_path": self.my_socket,
                # spawn-token boot accounting + container pre-dedication
                # (set by the daemon's _spawn_worker; absent for drivers)
                "spawn_token": os.environ.get("RT_SPAWN_TOKEN"),
                "env_hash": os.environ.get("RT_ENV_HASH"),
            },
        )
        self.node_id = info["node_id"]
        self.store = ShmStore(info["shm_name"])

    # -- controller reconnect (mirrors the daemon-side loop; reference:
    # drivers reconnect to a restarted GCS at its known address and the
    # job continues, `gcs_redis_failure_detector.h`) -------------------
    def _on_controller_lost(self, conn):
        if self._shutdown:
            return
        logger.warning("driver lost controller connection; reconnecting")
        asyncio.ensure_future(self._reconnect_controller())

    async def _reconnect_controller(self):
        deadline = time.monotonic() + self.cfg.controller_reconnect_timeout_s
        while time.monotonic() < deadline and not self._shutdown:
            try:
                conn = await rpc.connect_tcp(
                    *self._controller_addr, handler=self._handle,
                    name="controller",
                )
            except Exception as e:
                logger.debug("controller connect failed: %s", e)
                await asyncio.sleep(1.0)
                continue
            conn.on_close = self._on_controller_lost
            self.controller = conn
            # the restarted controller marked this incarnation's jobs
            # DEAD (drivers of the previous life are presumed gone):
            # re-register so job status reflects the live driver —
            # mirrors the daemon loop's register_node
            if self.mode == "driver":
                try:
                    await conn.call("register_job", {
                        "job_id": self.job_id.hex(), "pid": os.getpid(),
                    })
                except Exception:
                    logger.exception("job re-registration failed")
            # durable resubscribe: the restarted controller has no
            # memory of this connection's pubsub registrations — reset
            # the registered view and let the reconciler re-drive it
            # from desired state (serialized with any concurrent
            # subscribe/close, so a just-closed channel can't be
            # resurrected here)
            with self._state_lock:
                self._pubsub_registered.clear()
                self._pubsub_uncertain.clear()
            task = asyncio.ensure_future(self._pubsub_reconcile())
            task.add_done_callback(lambda t: t.cancelled() or t.exception())
            logger.info("driver reconnected to controller")
            return
        if not self._shutdown:
            logger.error("controller unreachable; driver calls will fail")

    @property
    def address(self) -> Tuple[str, str]:
        return (self.node_id, self.worker_id.hex())

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        # own-loop owner shards close their lease/noded conns on their
        # OWN loops (Task.cancel is loop-affine), then stop those loops
        for s in self._shards:
            if not s.shared:
                s.stop()

        async def _close():
            flush = getattr(self, "_flush_task", None)
            if flush is not None:
                flush.cancel()
            # push any queued borrow releases out before the routes die
            # (best-effort: owners also clean up on connection loss)
            try:
                await self._flush_ref_events(immediate=True)
            except Exception as e:
                logger.debug("final ref-event flush failed: %s", e)
            # final task-event drain so the last flush period's events
            # reach the controller before the connection dies
            events = self.task_events.drain()
            if events and self.controller is not None:
                try:
                    self.controller.send("report_task_events", {"events": events})
                    await asyncio.sleep(0.05)  # let the write flush
                except Exception as e:
                    logger.debug("final task-event report dropped: %s", e)
            # ... and the last obs frame (spans/metrics of a short-lived
            # process would otherwise never reach the collector)
            if self._ship_obs_frame():
                await asyncio.sleep(0.05)
            if self._server:
                await self._server.stop()
            for s in self._shards:
                if s.shared:
                    await s.close_shared()
            for conn in list(self._actor_conns.values()):
                await conn.close()
            if self.noded:
                await self.noded.close()
            if self.controller:
                await self.controller.close()

        try:
            asyncio.run_coroutine_threadsafe(_close(), self.loop).result(timeout=5)
        except Exception as e:
            logger.debug("io-loop close incomplete: %s", e)
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._io_thread.join(timeout=5)
        self._exec_pool.shutdown(wait=False)
        for pool in getattr(self, "_group_pools", {}).values():
            pool.shutdown(wait=False)
        if self.store:
            for id_bytes in self._held_pins:
                try:
                    self.store.release(id_bytes)
                except Exception as e:
                    logger.debug("releasing pin at shutdown: %s", e)
            self._held_pins.clear()
            self.store.close()

    # ------------------------------------------------------------------
    # helpers bridging threads
    # ------------------------------------------------------------------
    def _run(self, coro, timeout=None, block_grace=None):
        try:
            fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        except BaseException:
            # loop already closed (teardown race): the coroutine object
            # must be closed or CPython warns 'never awaited' at GC
            coro.close()
            raise
        notified = False
        remaining = timeout
        if block_grace is not None and (timeout is None
                                        or timeout > block_grace):
            # blocked-worker protocol (reference: raylet
            # HandleTaskBlocked): an in-task get that outlives the
            # grace window reports this worker as parked, releasing
            # its lease CPUs so the tasks that PRODUCE the awaited
            # objects (lineage re-derivation) can be scheduled — on a
            # freshly spawned worker when the whole pool is blocked.
            # Skipped entirely for timeouts at or under the grace: a
            # short-timeout poll must expire on ITS schedule.
            try:
                return fut.result(block_grace)
            except (TimeoutError, _FutureTimeoutError):
                if not fut.done():
                    notified = self._notify_blocked()
            if remaining is not None:
                remaining = max(0.0, remaining - block_grace)
        try:
            return fut.result(remaining)
        except (TimeoutError, _FutureTimeoutError) as e:
            # both spellings: before 3.11 concurrent.futures.TimeoutError
            # is NOT the builtin TimeoutError.  When the CORO itself
            # raised a timeout-flavored error (DeadlineExceeded on a ref,
            # user TimeoutError), surface it untouched; only an expired
            # WAIT becomes GetTimeoutError.  `fut.done()` alone can't
            # distinguish the two — the coro may complete in the window
            # between the wait expiring and this handler running — so
            # check whether `e` is actually the future's outcome.
            if fut.done():
                coro_err = fut.exception()
                if coro_err is e:
                    raise
                if coro_err is not None:
                    raise coro_err
                return fut.result()  # completed during the race window
            fut.cancel()
            raise exc.GetTimeoutError(
                f"timed out after {timeout}s", timeout_s=timeout
            )
        finally:
            if notified:
                self._notify_unblocked()

    async def _await_blocking_aware(self, coro, grace: float = 0.05):
        """Await `coro` on the io loop; when it outlives `grace`,
        report this worker blocked to the daemon (releasing its lease
        CPUs) until it completes — the async-path twin of the
        `block_grace` handling in `_run`."""
        if self.mode != "worker" or self.noded is None:
            return await coro
        task = asyncio.ensure_future(coro)
        done, _ = await asyncio.wait({task}, timeout=grace)
        if done:
            return task.result()
        notified = self._notify_blocked()
        try:
            return await task
        finally:
            if notified:
                self._notify_unblocked()

    def _notify_blocked(self) -> bool:
        """Count one parked operation; the daemon hears about the
        0 -> 1 transition only.  Several tasks can be parked on one
        worker concurrently (pipelined pushes, actor concurrency) —
        a per-operation send would let the FIRST task to resume mark
        the whole worker unblocked while its siblings still wait."""
        with self._blocked_ops_lock:
            self._blocked_ops += 1
            first = self._blocked_ops == 1
        if first:
            try:
                self.noded.send_threadsafe("worker_blocked", {})
            except Exception as e:
                logger.debug("worker_blocked notify failed: %s", e)
        return True

    def _notify_unblocked(self) -> None:
        with self._blocked_ops_lock:
            self._blocked_ops -= 1
            last = self._blocked_ops == 0
        if last:
            try:
                self.noded.send_threadsafe("worker_unblocked", {})
            except Exception as e:
                logger.debug("worker_unblocked notify failed: %s", e)

    # ------------------------------------------------------------------
    # cancellation (reference: CoreWorker::CancelTask + the executor's
    # cancellation wrapper `_raylet.pyx:2055`)
    # ------------------------------------------------------------------
    def cancel(self, ref: ObjectRef, force: bool = False):
        """Cancel the task that creates `ref` (reference: CancelTask +
        the Cython cancellation wrapper, `_raylet.pyx:2055`).

        Non-force: queued tasks are dropped; pushed-but-unstarted tasks
        are skipped by the executor; RUNNING normal tasks get
        TaskCancelledError raised asynchronously in their executing
        thread (lands at the next Python bytecode boundary — C-blocking
        calls finish first, same caveat as the reference's
        KeyboardInterrupt delivery).  force=True SIGKILLs the executing
        worker: the ref then fails with WorkerCrashedError, matching
        reference semantics; actor tasks reject force (killing the
        worker is `rt.kill(actor)`)."""
        task_id = ref.id.task_id().binary()
        with self._state_lock:
            pt = self.pending_tasks.get(task_id)
            if pt is None:
                return False  # finished or never ours
            if force and pt.spec.actor_id is not None:
                raise ValueError(
                    "force=True is not allowed for actor tasks; use "
                    "rt.kill(actor) to terminate the actor process"
                )
            pt.retries_left = 0  # a cancelled task never retries
            spec = pt.spec
            # 1. still in a local lease-pool queue: drop it here.
            # shard.lock nests inside _state_lock (documented order);
            # released before _fail_cancelled so the completion path's
            # own shard.lock acquisition can't self-deadlock
            dropped = False
            for shard in self._shards:
                with shard.lock:
                    for pool in shard.pools.values():
                        for queued in list(pool.queue):
                            if queued.task_id.binary() == task_id:
                                pool.queue.remove(queued)
                                dropped = True
                                break
                        if dropped:
                            break
                if dropped:
                    break
            if dropped:
                self._fail_cancelled(task_id, spec)
                return True
            # 1b. actor tasks are NEVER dropped owner-side: per-caller
            # seq_nos were assigned at submit and the executor's ordered
            # queue would wait forever on a gap — instead the cancel
            # rides the normal path and the executor replies
            # TaskCancelledError without running the method (seq chain
            # intact)
        # 2. pushed (or routed via noded): ask the execution side —
        # asynchronously (best-effort, like the reference): the caller
        # must not block while an actor connection establishes
        asyncio.run_coroutine_threadsafe(
            self._cancel_remote(task_id, spec, force), self.loop
        )
        return True

    async def _cancel_remote(self, task_id: bytes, spec: TaskSpec,
                             force: bool = False):
        conns = []
        lease_worker = None
        for shard in self._shards:
            with shard.lock:
                for pool, lease in shard.conn_lease.values():
                    if task_id in lease.assigned:
                        conns.append(lease.conn)
                        lease_worker = lease.worker_id
        if spec.actor_id is not None:
            with self._state_lock:
                c = self._actor_conns.get(spec.actor_id.binary())
            if c is not None:
                conns.append(c)
        if force:
            # reference force-cancel: kill the executing worker; the
            # pending task fails with worker_died -> WorkerCrashedError
            try:
                if lease_worker is not None:
                    await self.noded.call(
                        "kill_worker", {"worker_id": lease_worker},
                        timeout=10,
                    )
                    return
                # routed through a daemon (spillback/strategy): the
                # daemons find and kill the hosting worker
                reply = await self.noded.call(
                    "force_cancel_task", {"task_id": task_id},
                    timeout=10,
                )
                if reply and reply.get("killed"):
                    return
                # nobody is RUNNING it: it may still sit in a daemon
                # queue — fall through to the drop path below
            except Exception as e:
                logger.debug("cancel probe failed: %s", e)
                return
        if spec.actor_id is not None and not conns:
            # connection still being established: wait briefly so the
            # cancel can land on the executor before the task starts
            for _ in range(50):
                await asyncio.sleep(0.1)
                with self._state_lock:
                    c = self._actor_conns.get(spec.actor_id.binary())
                if c is not None:
                    conns.append(c)
                    break
        for conn in conns:
            try:
                # lease conns live on shard loops with owner_shards > 1:
                # call via the conn's own loop (rpc.call_on_conn_loop)
                reply = await rpc.call_on_conn_loop(
                    conn, "cancel_task", {"task_id": task_id}, timeout=5
                )
                if reply and reply.get("cancelled"):
                    return
            except Exception as e:
                logger.debug("cancel_task on executor failed: %s", e)
        # not found on any executor (e.g. queued in noded): best-effort
        try:
            await self.noded.call("cancel_task", {"task_id": task_id})
        except Exception as e:
            logger.debug("cancel_task via noded failed: %s", e)

    def _fail_cancelled(self, task_id: bytes, spec: TaskSpec):
        envelope = ser.serialize_to_bytes(
            exc.TaskCancelledError(task_id=spec.task_id),
            tag=ser.TAG_ERROR,
        )
        self._complete_task(TaskResult(
            task_id=spec.task_id, status="error", error=envelope,
        ))

    async def _h_cancel_task(self, payload, conn):
        """Executor side: drop the task if it has not started; if it IS
        running (normal tasks only), raise TaskCancelledError in its
        executing thread (reference: the Cython wrapper delivering
        KeyboardInterrupt into the running task, `_raylet.pyx:2055`).
        The exception lands at the next bytecode boundary."""
        task_id = payload["task_id"]
        started = getattr(self, "_started_tasks", None)
        if started is None:
            started = self._started_tasks = set()
        if task_id in started:
            # check-and-raise under _state_lock: _call registers/pops
            # its thread ident under the same lock, so the ident cannot
            # be recycled onto a DIFFERENT task between our lookup and
            # the raise (the pending exception lands while the victim
            # thread is still inside its own _call frame)
            import ctypes

            with self._state_lock:
                tid = self._task_threads.get(task_id)
                if tid is not None:
                    n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                        ctypes.c_ulong(tid),
                        ctypes.py_object(exc.TaskCancelledError),
                    )
                    if n == 1:
                        return {"cancelled": True, "interrupted": True}
                    if n > 1:  # raced a thread swap: undo, never poison
                        ctypes.pythonapi.PyThreadState_SetAsyncExc(
                            ctypes.c_ulong(tid), None
                        )
            return {"cancelled": False}  # already executing
        cancelled = self._cancelled_tasks = getattr(
            self, "_cancelled_tasks", set()
        )
        cancelled.add(task_id)
        return {"cancelled": True}

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------
    def put(self, value: Any, *, inline: Optional[bool] = None) -> ObjectRef:
        """`inline=None` (default) picks by size: small objects stay
        in the owner's memory and every borrower fetch is an owner RPC.
        `inline=False` forces the shm path regardless of size — the
        BROADCAST shape: one write, then every node-local borrower
        reads zero-copy and remote nodes pull once per node instead of
        once per borrower (an N-runner weight broadcast was N owner
        round-trips per version through the daemon's route path;
        measured in PERF.md's rllib section)."""
        self._put_counter += 1
        scope = getattr(self._task_local, "task_id", None) or TaskID.for_job(self.job_id)
        oid = ObjectID.for_put(scope, self._put_counter)
        chunks, total, captured = ser.serialize(value)
        if captured:
            # tie borrows to THIS container so they release when the
            # put object is freed, not at job exit.  Self-owned refs go
            # through the counted selfborrow path too (a boolean pin
            # clobbers when one inner sits in two containers).
            with self._state_lock:
                self._register_contained(oid.binary(), [
                    (r.binary(), tuple(r.owner))
                    for r in captured
                    if r.owner is not None
                ])
        st = _ObjectState(ready=asyncio.Event())
        if (total <= self.cfg.max_direct_call_object_size
                and inline is not False):
            buf = bytearray(total)
            ser.write_chunks(chunks, memoryview(buf))
            st.where, st.value, st.size = _INLINE, bytes(buf), total
        else:
            from ray_tpu.shm import StoreFullError

            deadline = time.time() + 30.0
            attempts = 0
            disk_full_streak = 0
            while True:
                try:
                    dest = self.store.create(
                        oid.binary(), total, allow_evict=False
                    )
                    break
                except StoreFullError:
                    if time.time() > deadline:
                        raise
                    reply = None
                    try:
                        # watermark spills first, full drain once the
                        # create stays blocked (fragmentation)
                        reply = self.noded_call(
                            "spill_now", {"drain": attempts >= 2},
                            timeout=10,
                        )
                    except Exception as e:
                        logger.debug("spill_now nudge failed: %s", e)
                    disk_full_streak = _spill_clamp_streak(
                        reply, disk_full_streak
                    )
                    attempts += 1
                    time.sleep(0.05)
            ser.write_chunks(chunks, dest)
            if self.cfg.object_integrity_verify_get:
                # seal-time checksum for the opt-in local-get verifier,
                # computed over the write buffer BEFORE sealing — a
                # re-get after seal could race the spill pass (the
                # freshly sealed, unpinned object is a spill candidate)
                # and fail a put that actually succeeded
                from ray_tpu.core import integrity as _integrity

                st.checksum = _integrity.checksum(dest)
            del dest
            self.store.seal(oid.binary())
            st.where, st.node_id, st.size = _SHM, self.node_id, total
        st.ready.set()
        with self._state_lock:
            self.objects[oid.binary()] = st
            self._add_local_ref(oid.binary())
        return ObjectRef(oid, self.address, st.size, _register=True)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ObjectRef)
        if single:
            refs = [refs]

        # Fast path: owned objects that are already ready and inline
        # deserialize in the calling thread — no event-loop round trip
        # (reference: in-process memory store hits skip the plasma
        # path the same way).  Event.is_set() is a thread-safe read.
        # A partial hit keeps the prefix and round-trips only the rest.
        vals = []
        for r in refs:
            st = self.objects.get(r.binary())
            if (
                st is not None
                and st.ready.is_set()
                and st.error is None
                and st.where == _INLINE
                and st.value is not None
            ):
                tag, val = ser.deserialize(memoryview(st.value))
                vals.append(_unwrap(tag, val))
            else:
                break
        if len(vals) == len(refs):
            return vals[0] if single else vals
        rest = refs[len(vals):]

        async def _get_all():
            primed = await self._prime_borrowed(rest)
            try:
                return await asyncio.gather(
                    *[self._get_one(r) for r in rest]
                )
            finally:
                for b in primed:  # drop unconsumed entries (cancel/error)
                    self._primed_replies.pop(b, None)

        # in-task gets report blocked-worker state past a short grace
        # window, so a worker parked on a not-yet-derivable object
        # frees its CPUs for the producing tasks (never for driver
        # gets — the driver holds no lease)
        block_grace = (
            0.05 if (self.mode == "worker" and self.noded is not None
                     and getattr(self._task_local, "task_id", None)
                     is not None)
            else None
        )
        try:
            vals.extend(self._run(_get_all(), timeout=timeout,
                                  block_grace=block_grace))
        except exc.GetTimeoutError as e:
            if e.object_id is None:
                # attach the first still-pending ref: the one the
                # caller was actually stuck on
                for r in rest:
                    st = self.objects.get(r.binary())
                    if st is None or not st.ready.is_set():
                        e.object_id = r.id
                        break
            raise
        return vals[0] if single else vals

    def wait(self, refs: List[ObjectRef], num_returns=1, timeout=None,
             fetch_local=True):
        return self._run(self._wait(refs, num_returns, timeout))

    # ------------------------------------------------------------------
    # normal task submission — thread-side fast path
    # ------------------------------------------------------------------
    def submit_task(self, fn, args, kwargs, **options) -> List[ObjectRef]:
        renv = options.get("runtime_env")
        env_hash = None
        if renv:
            # tasks with a runtime env run on DEDICATED workers keyed
            # by env hash (reference: worker-pool runtime-env matching)
            from ray_tpu.core.runtime_env import (
                runtime_env_hash,
                validate_runtime_env,
            )

            validate_runtime_env(renv)

            renv = self._run(self._prepare_runtime_env(dict(renv)))
            env_hash = runtime_env_hash(renv)
        fid, blob = self._export_function(fn)
        task_id = TaskID.for_job(self.job_id)
        num_returns = options.get("num_returns", 1)
        if num_returns == "streaming":
            num_returns = STREAMING
        transit: list = []
        resolved, kwargs = self._resolve_args_kwargs(args, kwargs, transit)
        spec = TaskSpec(
            task_id=task_id,
            function_id=fid,
            function_blob=blob,
            args=resolved,
            kwargs=kwargs,
            num_returns=num_returns,
            owner=self.address,
            resources=Resources.from_options(options),
            max_retries=options.get("max_retries", self.cfg.task_max_retries),
            retry_exceptions=options.get("retry_exceptions", False),
            strategy=_strategy_from_options(options),
            name=options.get("name", getattr(fn, "__name__", "task")),
            runtime_env=renv,
            env_hash=env_hash,
            deadline_s=self._effective_deadline(options),
        )
        from ray_tpu.util import tracing as _tracing

        spec.trace_ctx = _tracing.make_submit_ctx(spec.name)
        refs = []
        with self._state_lock:
            for oid in spec.return_ids():
                self.objects[oid.binary()] = _ObjectState(ready=asyncio.Event())
                self.lineage[oid.binary()] = spec
                self._add_local_ref(oid.binary())
                refs.append(ObjectRef(oid, self.address, _register=True))
            if num_returns == STREAMING:
                self._streams[spec.task_id.binary()] = _StreamState(
                    event=asyncio.Event()
                )
            self.pending_tasks[spec.task_id.binary()] = _PendingTask(
                spec, spec.max_retries, transit
            )
            n_lineage = len(refs)  # one retained lineage entry per return
            for a in spec.args:
                if isinstance(a, ArgRef):
                    rc = self.refs.get(a.id_bytes)
                    if rc:
                        rc.submitted += 1
                        rc.lineage += n_lineage
        self.task_events.record(spec.task_id.binary(), spec.name, "SUBMITTED")
        # per-shard accounting (normal tasks): pairs with the completed
        # bump at the exactly-once pop in completion.complete_task
        shard = self._shard_for(spec.task_id.binary())
        with shard.lock:
            shard.submitted += 1
        _mdefs.inc("rt_owner_tasks_submitted_total",
                   tags={"shard": str(shard.index)})
        if spec.deadline_s is not None:
            self._arm_deadline(spec)
        self._push_or_queue(spec)
        if num_returns == STREAMING:
            return ObjectRefGenerator(spec.task_id.binary(), self)
        return refs

    # ------------------------------------------------------------------
    # end-to-end deadlines (`.options(timeout_s=...)`)
    # ------------------------------------------------------------------
    def _effective_deadline(self, options) -> Optional[float]:
        """Absolute monotonic deadline for a new submission: the
        caller's explicit timeout_s combined (min) with the AMBIENT
        deadline of the task currently executing in this thread — so
        nested `.remote()` calls inherit the shrinking budget of their
        parent (gRPC-style deadline propagation)."""
        deadline = None
        timeout_s = options.get("timeout_s")
        if timeout_s is not None:
            timeout_s = float(timeout_s)
            if timeout_s <= 0:
                raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
            deadline = time.monotonic() + timeout_s
        ambient = _ambient_deadline.get()
        if ambient is not None:
            deadline = ambient if deadline is None else min(deadline, ambient)
        return deadline

    def _arm_deadline(self, spec: TaskSpec):
        """Owner-side watchdog: when the deadline passes with the task
        still pending, fail it with DeadlineExceededError — the caller
        gets an answer even when the executor side is partitioned away
        and no failure result will ever arrive."""
        tid = spec.task_id.binary()
        deadline = spec.deadline_s

        def _arm():
            handle = self.loop.call_later(
                max(0.0, deadline - time.monotonic()),
                self._deadline_fire, tid,
            )
            with self._state_lock:
                pt = self.pending_tasks.get(tid)
            if pt is None:
                handle.cancel()  # completed before the watchdog armed
            else:
                pt.deadline_timer = handle

        try:
            self.loop.call_soon_threadsafe(_arm)
        except RuntimeError:
            pass  # loop closed (teardown race)

    def _deadline_fire(self, tid: bytes):
        with self._state_lock:
            pt = self.pending_tasks.get(tid)
            if pt is None:
                return  # completed in time
            dl = pt.spec.deadline_s
            if dl is None or time.monotonic() < dl:
                return
            pt.retries_left = 0  # an expired task never retries
            attempts = pt.attempts
            spec = pt.spec
        err = exc.DeadlineExceededError(
            f"task {spec.name!r} exceeded its deadline "
            f"(timeout_s elapsed; {attempts} retries were attempted); "
            f"the caller gave up, so the task will not be resubmitted",
        )
        envelope = ser.serialize_to_bytes(err, tag=ser.TAG_ERROR)
        self._complete_task(TaskResult(
            task_id=spec.task_id, status="error", error=envelope,
        ))
        # best-effort: tell whoever holds the work to stop running it
        task = asyncio.ensure_future(self._cancel_remote(tid, spec, False))
        task.add_done_callback(lambda t: t.cancelled() or t.exception())

    def _export_function(self, fn) -> Tuple[bytes, Optional[bytes]]:
        # keyed by id(fn) with the FUNCTION PINNED in the entry AND an
        # identity check on hit: without both, a GC'd function's address
        # can be reused by a brand-new function, which would silently
        # inherit the old export and run the WRONG code on the executor.
        # Growth is bounded by distinct exported functions — the same
        # lifetime _fn_cache (fid -> fn) already has, mirroring the
        # reference's per-job function table.
        cached = self._fn_export.get(id(fn))
        if cached is not None and cached[2] is fn:
            fid, _blob, _pin = cached
            return fid, None  # executors kv_get on miss
        blob = ser.dumps_oob(fn)
        fid = function_id_of(blob)
        self._fn_export[id(fn)] = (fid, blob, fn)
        self._fn_cache[fid] = fn
        if fid not in self._exported_fids:
            self._exported_fids.add(fid)
            key = "fn:" + fid.hex()
            self.controller.send_threadsafe("kv_put_oneway", {"key": key, "value": blob})
        return fid, blob

    def _resolve_args_sync(self, args, transit=None) -> Optional[List[Any]]:
        """Fast path: all ObjectRef args already ready.  Returns None if
        a pending ref forces the async path."""
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                st = self.objects.get(a.binary())
                if st is None:
                    out.append(ArgRef(a.binary(), a.owner))
                elif st.ready.is_set():
                    if st.error is not None:
                        raise _error_from_envelope(st.error)
                    if st.where == _INLINE:
                        out.append(("__rt_inline__", st.value))
                    else:
                        out.append(ArgRef(a.binary(), a.owner))
                else:
                    return None
            else:
                out.append(self._inline_value_arg(a, transit))
        return out

    async def _resolve_args_async(self, args, transit=None) -> List[Any]:
        """Dependency resolution (reference: `dependency_resolver.h`)."""
        out = []
        for a in args:
            if isinstance(a, ObjectRef):
                st = self.objects.get(a.binary())
                if st is not None:
                    await st.ready.wait()
                    if st.error is not None:
                        raise _error_from_envelope(st.error)
                    if st.where == _INLINE:
                        out.append(("__rt_inline__", st.value))
                    else:
                        out.append(ArgRef(a.binary(), a.owner))
                else:
                    out.append(ArgRef(a.binary(), a.owner))
            else:
                out.append(self._inline_value_arg(a, transit))
        return out

    def _resolve_args_kwargs(self, args, kwargs, transit=None):
        """Resolve positional args AND kwarg values together (top-level
        ObjectRefs in either position resolve before execution, like the
        reference).  Returns (resolved_args, resolved_kwargs)."""
        keys = list(kwargs)
        combined = list(args) + [kwargs[k] for k in keys]
        resolved = self._resolve_args_sync(combined, transit)
        if resolved is None:
            resolved = self._run(self._resolve_args_async(combined, transit))
        return (
            resolved[: len(args)],
            dict(zip(keys, resolved[len(args):])),
        )

    def _inline_value_arg(self, v, transit=None) -> Tuple[str, bytes]:
        """Serialize a plain (non-ref) argument into an inline envelope
        at submission time.  The spec then carries only bytes + ids, so
        every relaying daemon can deserialize the FRAME even when the
        value references modules only driver/executor import, and a
        value that fails to deserialize on the executor surfaces as
        that task's error, not a poisoned connection (reference: args
        travel as serialized buffers, materialized by the executor —
        `dependency_resolver.h` / plasma args)."""
        chunks, total, captured = ser.serialize(v)
        if captured:
            self._pin_contained(captured)
            if transit is not None:
                self._pin_transit(captured, transit)
        buf = bytearray(total)
        ser.write_chunks(chunks, memoryview(buf))
        return ("__rt_inline__", bytes(buf))

    def _pin_transit(self, captured_refs, transit: list):
        """Transit-pin FOREIGN-owned refs being forwarded inside a
        serialized message: our registered borrow at the owner must
        outlive the message, or the owner could free the object while
        it is in flight (the forwarded-ref window of the reference's
        borrower protocol).  Pins release at the carrying task's final
        completion (`_complete_task`)."""
        with self._state_lock:
            for r in captured_refs:
                if r.owner is None or tuple(r.owner) == self.address:
                    continue
                rc = self.refs.setdefault(r.binary(), _RefCount())
                rc.transit += 1
                rc.owner_addr = rc.owner_addr or tuple(r.owner)
                transit.append((r.binary(), tuple(r.owner)))

    def _release_transit(self, entries):
        """Drop transit pins; caller holds `_state_lock`."""
        for inner_id, owner in entries:
            rc = self.refs.get(inner_id)
            if rc is None:
                continue
            rc.transit -= 1
            rc.owner_addr = rc.owner_addr or tuple(owner)
            self._maybe_free(inner_id)

    # ref-event channel tuning: a flush window long enough to coalesce
    # a churn burst, short enough to be latency-invisible next to the
    # object-free paths it feeds
    _REF_EVENT_FLUSH_S = 0.005
    _REF_EVENT_MAX_BATCH = 1024
    # bulk location/value lookup chunk (see _prime_borrowed)
    _BULK_GET_CHUNK = 512

    def _send_remove_borrow(self, inner_id: bytes, owner):
        self._queue_ref_event(
            tuple(owner), "remove_borrow",
            {"id": inner_id, "borrower": self.address},
        )

    def _queue_ref_event(self, owner: tuple, method: str, payload: dict):
        """Queue an un-ACK'd borrow event for the coalesced per-owner
        channel (reference: `src/ray/pubsub/README.md` — the fan-in
        argument: O(#subscribers) messages instead of O(#objects);
        `reference_count.h:64` WaitForRefRemoved).  Events to one owner
        preserve queue order; ACK'd registrations stay direct RPCs (the
        ACK future is awaited individually) and always precede any
        queued remove for the same ref causally."""
        if self.noded is None:
            return
        with self._ref_event_lock:
            q = self._ref_event_queues.setdefault(owner, [])
            q.append((method, payload))
            # boundary transition only: a burst past MAX must not spawn
            # one no-op flush coroutine per further event
            full = len(q) % self._REF_EVENT_MAX_BATCH == 0
            schedule = not self._ref_event_flush_scheduled
            if schedule:
                self._ref_event_flush_scheduled = True
        if schedule or full:
            try:
                asyncio.run_coroutine_threadsafe(
                    self._flush_ref_events(immediate=full), self.loop
                ).add_done_callback(
                    lambda f: f.exception() if not f.cancelled() else None
                )
            except Exception as e:
                logger.debug("scheduling ref-event flush failed: %s", e)
                with self._ref_event_lock:
                    self._ref_event_flush_scheduled = False

    async def _flush_ref_events(self, immediate: bool = False):
        if not immediate:
            await asyncio.sleep(self._REF_EVENT_FLUSH_S)
        while True:
            with self._ref_event_lock:
                batches = self._ref_event_queues
                self._ref_event_queues = {}
                if not batches:
                    self._ref_event_flush_scheduled = False
                    return
            for owner, events in batches.items():
                for i in range(0, len(events), self._REF_EVENT_MAX_BATCH):
                    try:
                        self.noded.send_threadsafe("route", {
                            "target": owner,
                            "method": "ref_events",
                            "payload": {
                                "events": events[
                                    i:i + self._REF_EVENT_MAX_BATCH
                                ],
                            },
                            "want_reply": False,
                        })
                    except Exception as e:
                        # daemon gone: owner cleanup handles it
                        logger.debug("ref-event batch dropped: %s", e)
                        break

    # args at least this big make their node the preferred executor
    # (reference: locality-aware lease policy, `lease_policy.h` — pull
    # the task to the data, not the data to the task)
    _LOCALITY_MIN_ARG_BYTES = 1024 * 1024

    def _locality_node(self, spec: TaskSpec) -> Optional[str]:
        """Node holding the largest shm-resident arg above the locality
        threshold, if it isn't this node."""
        best_node, best_size = None, self._LOCALITY_MIN_ARG_BYTES
        for a in [*spec.args, *spec.kwargs.values()]:
            if not isinstance(a, ArgRef):
                continue
            st = self.objects.get(a.id_bytes)
            if (
                st is not None
                and st.where == _SHM
                and st.node_id
                and st.node_id != self.node_id
                and (st.size or 0) >= best_size
            ):
                best_node, best_size = st.node_id, st.size
        return best_node

    def _push_or_queue(self, spec: TaskSpec):
        if spec.strategy.kind != "default":
            # placement-constrained tasks go through the node daemon,
            # which consults the controller for PG bundles / affinity /
            # spread targets (reference: lease policy + spillback)
            try:
                self.noded.send_threadsafe("submit_task", spec)
            except rpc.ConnectionLost:
                pass
            return
        locality = self._locality_node(spec)
        if locality is not None:
            # route to the data's node (soft: falls back if it's gone)
            spec.strategy = SchedulingStrategy(
                kind="node_affinity", node_id=locality, soft=True
            )
            try:
                self.noded.send_threadsafe("submit_task", spec)
            except rpc.ConnectionLost:
                pass
            return
        # default strategy: the shard keyed by this task id owns the
        # push (its lease pools, its loop, its daemon connection)
        self._shard_for(spec.task_id.binary()).push(spec)

    # ------------------------------------------------------------------
    # actor creation + actor task submission
    # ------------------------------------------------------------------
    def create_actor(self, cls, args, kwargs, **options):
        return self._run(self._create_actor(cls, args, kwargs, options))

    async def _prepare_runtime_env(self, renv):
        """Driver-side prep shared by actors AND tasks: package local
        py_modules, ship once via KV; the spec carries only (name, key)
        pairs (reference: runtime_env packaging uploads to the GCS,
        `runtime_env/packaging.py`)."""
        if not (renv and renv.get("py_modules")):
            return renv
        from ray_tpu.core.runtime_env import (
            _module_root,
            module_stat_sig,
            package_py_modules,
        )

        uploaded = getattr(self, "_pymod_uploaded", None)
        if uploaded is None:
            uploaded = self._pymod_uploaded = set()
        pkg_cache = getattr(self, "_pymod_pkg_cache", None)
        if pkg_cache is None:
            pkg_cache = self._pymod_pkg_cache = {}
        entries = []
        for mod in renv["py_modules"]:
            # repeat creations (actor fleets) skip BOTH the re-zip
            # and the re-upload: a stat-walk signature detects
            # unchanged trees far cheaper than deflate
            root = _module_root(mod)
            sig = module_stat_sig(root)
            cached = pkg_cache.get(root)
            if cached is not None and cached[0] == sig:
                entries.append((cached[1], cached[2]))
                continue
            # deflate over a whole module tree takes long enough to
            # stall every task on the loop — zip off-loop
            [(name, key, pkg_blob)] = await self.loop.run_in_executor(
                None, package_py_modules, [root]
            )
            if key not in uploaded and not await self.controller.call(
                "kv_exists", {"key": key}
            ):
                await self.controller.call(
                    "kv_put", {"key": key, "value": pkg_blob}
                )
            uploaded.add(key)
            pkg_cache[root] = (sig, name, key)
            entries.append((name, key))
        renv = dict(renv)
        renv["py_modules"] = entries
        return renv

    async def _create_actor(self, cls, args, kwargs, options):
        renv = options.get("runtime_env")
        if renv:
            from ray_tpu.core.runtime_env import validate_runtime_env

            validate_runtime_env(renv)
        if renv and renv.get("py_modules"):
            options = dict(options)
            options["runtime_env"] = await self._prepare_runtime_env(renv)
        blob = ser.dumps_oob(cls)
        cid = function_id_of(blob)
        actor_id = ActorID.of(self.job_id)
        is_async = any(
            asyncio.iscoroutinefunction(getattr(cls, m, None))
            for m in dir(cls)
            if not m.startswith("__")
        )
        import inspect as _inspect

        streaming_methods = tuple(
            m for m in dir(cls)
            if not m.startswith("_")
            and (_inspect.isgeneratorfunction(getattr(cls, m, None))
                 or _inspect.isasyncgenfunction(getattr(cls, m, None)))
        )
        # @rt.method(concurrency_group=...) defaults, recorded in the
        # spec so get_actor-rebuilt handles route the same lanes
        method_groups = {
            m: getattr(cls, m).__rt_method_options__["concurrency_group"]
            for m in dir(cls)
            if not m.startswith("_")
            and getattr(getattr(cls, m, None),
                        "__rt_method_options__", {}).get("concurrency_group")
        }
        concurrency_groups = dict(options.get("concurrency_groups") or {})
        for name, limit in concurrency_groups.items():
            if not isinstance(limit, int) or limit < 1:
                raise ValueError(
                    f"concurrency_groups[{name!r}] must be a positive "
                    f"int, got {limit!r}"
                )
        for m, g in method_groups.items():
            if g not in concurrency_groups:
                raise ValueError(
                    f"@method(concurrency_group={g!r}) on {m!r} names an "
                    f"undeclared group; declare it in concurrency_groups"
                )
        init_transit: list = []
        spec = ActorCreationSpec(
            actor_id=actor_id,
            class_id=cid,
            class_blob=blob,
            init_args=await self._resolve_args_async(args, init_transit),
            init_kwargs={
                k: (await self._resolve_args_async([v], init_transit))[0]
                for k, v in kwargs.items()
            },
            owner=self.address,
            resources=Resources.from_options(options),
            max_restarts=options.get("max_restarts", self.cfg.actor_max_restarts),
            max_task_retries=options.get("max_task_retries", 0),
            max_concurrency=options.get("max_concurrency", 1),
            # groups imply concurrent lanes -> event-loop dispatch
            is_async=(is_async or options.get("max_concurrency", 1) > 1
                      or bool(concurrency_groups)),
            name=options.get("name"),
            namespace=options.get("namespace", "default"),
            streaming_methods=streaming_methods,
            strategy=_strategy_from_options(options),
            lifetime=options.get("lifetime"),
            runtime_env=options.get("runtime_env"),
            concurrency_groups=concurrency_groups or None,
            method_groups=method_groups or None,
            allow_out_of_order=bool(
                options.get("allow_out_of_order_execution", False)
            ),
            has_async_methods=is_async,
        )
        try:
            reply = await self.controller.call("create_actor", spec)
        finally:
            # forwarded foreign refs in init args stay transit-pinned
            # until the create reply — by then the actor worker has
            # deserialized them and registered its own borrows
            with self._state_lock:
                self._release_transit(init_transit)
        if not reply.get("ok"):
            raise exc.RayTpuError(reply.get("error", "actor creation failed"))
        self._actor_addr[actor_id.binary()] = tuple(reply["address"])
        return actor_id, reply["address"], streaming_methods, method_groups

    def submit_actor_task(self, handle, method_name, args, kwargs, **options):
        aid = handle._actor_id.binary()
        task_id = TaskID.for_actor_task(handle._actor_id)
        num_returns = options.get("num_returns", 1)
        if num_returns == "streaming":
            num_returns = STREAMING
        transit: list = []
        resolved, kwargs = self._resolve_args_kwargs(args, kwargs, transit)
        kwargs["__rt_method__"] = method_name
        # per-call lane, or the @rt.method default recorded on the
        # handle; rides a reserved kwarg so the TaskSpec wire schema
        # stays unchanged.  An EXPLICIT concurrency_group=None routes
        # to the default lane even when the method declares a default.
        if "concurrency_group" in options:
            group = options["concurrency_group"]
        else:
            group = getattr(handle, "_method_groups", {}).get(method_name)
        if group is not None:
            kwargs["__rt_group__"] = group
        spec = TaskSpec(
            task_id=task_id,
            function_id=b"",
            function_blob=None,
            args=resolved,
            kwargs=kwargs,
            num_returns=num_returns,
            owner=self.address,
            resources=Resources(num_cpus=0),
            max_retries=options.get("max_retries", handle._max_task_retries),
            strategy=SchedulingStrategy(),
            name=f"{handle._class_name}.{method_name}",
            actor_id=handle._actor_id,
            seq_no=handle._next_seq(group),
            deadline_s=self._effective_deadline(options),
        )
        from ray_tpu.util import tracing as _tracing

        spec.trace_ctx = _tracing.make_submit_ctx(spec.name)
        refs = []
        with self._state_lock:
            for oid in spec.return_ids():
                self.objects[oid.binary()] = _ObjectState(ready=asyncio.Event())
                # actor-task returns reconstruct by re-executing the
                # method on the (live) actor — but ONLY when the call
                # opted into retries: re-running a non-idempotent method
                # behind the user's back can double-apply side effects
                # (reference: actor outputs are reconstructable only
                # with max_task_retries > 0, `task_manager.h` lineage)
                if spec.max_retries > 0:
                    self.lineage[oid.binary()] = spec
                self._add_local_ref(oid.binary())
                refs.append(ObjectRef(oid, self.address, _register=True))
            if num_returns == STREAMING:
                self._streams[spec.task_id.binary()] = _StreamState(
                    event=asyncio.Event()
                )
            self.pending_tasks[spec.task_id.binary()] = _PendingTask(
                spec, spec.max_retries, transit
            )
            # lineage entries exist only for retry-opted calls (above)
            n_lineage = len(refs) if spec.max_retries > 0 else 0
            for a in spec.args:
                if isinstance(a, ArgRef):
                    rc = self.refs.get(a.id_bytes)
                    if rc:
                        rc.submitted += 1
                        rc.lineage += n_lineage
            if handle._address is not None:
                self._actor_addr.setdefault(aid, tuple(handle._address))
        self.task_events.record(spec.task_id.binary(), spec.name, "SUBMITTED")
        _mdefs.inc("rt_owner_tasks_submitted_total", tags={"shard": "actor"})
        if spec.deadline_s is not None:
            self._arm_deadline(spec)
        self._push_actor_task(aid, spec)
        if num_returns == STREAMING:
            return ObjectRefGenerator(spec.task_id.binary(), self)
        return refs

    def _push_actor_task(self, aid: bytes, spec: TaskSpec):
        with self._state_lock:
            conn = self._actor_conns.get(aid)
            if conn is not None and not conn.closed:
                self._actor_assigned.setdefault(conn, {})[spec.task_id.binary()] = spec
            else:
                self._actor_queue.setdefault(aid, deque()).append(spec)
                need_connect = aid not in self._actor_connecting
                if need_connect:
                    self._actor_connecting.add(aid)
                conn = None
        if conn is not None:
            try:
                conn.send_threadsafe("execute_task", spec)
            except rpc.ConnectionLost:
                pass  # teardown fails/retries via _on_actor_conn_closed
        elif need_connect:
            self.loop.call_soon_threadsafe(
                lambda: asyncio.ensure_future(self._connect_actor(aid))
            )

    async def _connect_actor(self, aid: bytes):
        try:
            addr = self._actor_addr.get(aid)
            # resolve (and refresh after restart) via the controller
            info = await self.controller.call("get_actor", {"actor_id": aid})
            if info is None or info["state"] == "DEAD":
                self._fail_actor_queue(aid, info)
                return
            if info["state"] != "ALIVE":
                for _ in range(600):
                    await asyncio.sleep(0.1)
                    info = await self.controller.call("get_actor", {"actor_id": aid})
                    if info is None or info["state"] in ("ALIVE", "DEAD"):
                        break
                if info is None or info["state"] != "ALIVE":
                    self._fail_actor_queue(aid, info)
                    return
            old_addr = addr
            addr = tuple(info["address"])
            self._actor_addr[aid] = addr
            if old_addr is not None and tuple(old_addr) != addr:
                # restarted actor landed on a new worker: the retired
                # address never comes back, so evict its breaker
                rpc.drop_breaker(f"actor:{old_addr[0]}:{old_addr[1]}")
            breaker = rpc.breaker_for(f"actor:{addr[0]}:{addr[1]}")
            if not breaker.allow():
                # breaker open: don't even dial — the backoff path below
                # retries after the half-open cooldown
                raise rpc.ConnectionLost(
                    f"circuit breaker open for actor address {addr}"
                )
            sock = await self.noded.call(
                "resolve_worker_socket",
                {"node_id": addr[0], "worker_id": addr[1]},
            )
            if sock is None:
                # remote node without reachable socket: relay via noded
                self._drain_actor_queue_via_noded(aid, addr)
                return
            try:
                conn = await rpc.connect_unix(
                    sock, handler=self._handle, name=f"actor-{aid.hex()[:8]}"
                )
            except Exception:
                breaker.record_failure()
                raise
            breaker.record_success()
            with self._state_lock:
                self._actor_connect_attempts.pop(aid, None)
            conn.on_close = lambda c: self._on_actor_conn_closed(aid, c)
            with self._state_lock:
                self._actor_conns[aid] = conn
                q = self._actor_queue.get(aid)
                specs = list(q) if q else []
                if q:
                    q.clear()
                assigned = self._actor_assigned.setdefault(conn, {})
                for s in specs:
                    assigned[s.task_id.binary()] = s
            for s in specs:
                conn.send_threadsafe("execute_task", s)
        except Exception as e:
            logger.debug("actor task push failed: %s", e)
            # stale address or races with restart: retry while callers
            # still have queued work — through the capped jittered
            # backoff schedule, NOT a fixed-delay redial loop (a dead
            # address would otherwise be hammered at 5 Hz forever)
            with self._state_lock:
                attempts = self._actor_connect_attempts.get(aid, 0)
                self._actor_connect_attempts[aid] = attempts + 1
            await asyncio.sleep(backoff_delay_s(
                attempts,
                base_s=self.cfg.task_retry_backoff_base_ms / 1000.0,
                cap_s=self.cfg.task_retry_backoff_max_ms / 1000.0,
                floor_s=0.2,  # the historical fixed redial delay
                rng=self._retry_rng,
            ))
            with self._state_lock:
                retry = bool(self._actor_queue.get(aid))
            if retry and not self._shutdown:
                asyncio.ensure_future(self._retry_connect_actor(aid))
        finally:
            self._actor_connecting.discard(aid)

    async def _retry_connect_actor(self, aid: bytes):
        with self._state_lock:
            if aid in self._actor_connecting:
                return
            self._actor_connecting.add(aid)
        await self._connect_actor(aid)

    def _drain_actor_queue_via_noded(self, aid: bytes, addr):
        with self._state_lock:
            q = self._actor_queue.get(aid)
            specs = list(q) if q else []
            if q:
                q.clear()
        for s in specs:
            self.noded.send("submit_actor_task", {"spec": s, "actor_addr": addr})

    def _fail_actor_queue(self, aid: bytes, info):
        cause = (info or {}).get("death_cause", "actor not found")
        envelope = ser.serialize_to_bytes(
            exc.ActorDiedError(f"actor is dead: {cause}"), tag=ser.TAG_ERROR
        )
        with self._state_lock:
            q = self._actor_queue.pop(aid, None)
            specs = list(q) if q else []
            dead_addr = self._actor_addr.pop(aid, None)
        if dead_addr is not None:
            # terminal death: the address is retired with the actor
            rpc.drop_breaker(f"actor:{dead_addr[0]}:{dead_addr[1]}")
        for s in specs:
            self._complete_task(
                TaskResult(task_id=s.task_id, status="error", error=envelope)
            )

    def _on_actor_conn_closed(self, aid: bytes, conn: rpc.Connection):
        with self._state_lock:
            if self._actor_conns.get(aid) is conn:
                del self._actor_conns[aid]
            assigned = self._actor_assigned.pop(conn, {})
        for spec in assigned.values():
            self._complete_task(
                TaskResult(task_id=spec.task_id, status="worker_died")
            )

    # ------------------------------------------------------------------
    # task completion (io thread)
    # ------------------------------------------------------------------
    async def _flush_task_events_loop(self):
        """Batched flush to the controller (reference:
        `task_event_buffer.h:220` periodic flush — never the hot path).
        The same loop carries the observability plane's frames: every
        `metrics_report_interval_ms` it ships ONE `report_obs` frame
        holding this process's metrics-registry snapshot and the spans
        finished since the last flush — batched like the task events,
        never a per-sample RPC."""
        from ray_tpu.core.task_events import FLUSH_PERIOD_S

        obs_period_s = max(
            FLUSH_PERIOD_S, self.cfg.metrics_report_interval_ms / 1000.0
        )
        last_obs = 0.0
        while not self._shutdown:
            await asyncio.sleep(FLUSH_PERIOD_S)
            events = self.task_events.drain()
            if events and self.controller is not None:
                try:
                    self.controller.send(
                        "report_task_events", {"events": events}
                    )
                except Exception as e:
                    logger.debug("task-event report dropped: %s", e)
            now = time.monotonic()
            if now - last_obs >= obs_period_s:
                last_obs = now
                self._ship_obs_frame()

    def _ship_obs_frame(self) -> bool:
        """Send one batched obs frame (metrics snapshot + drained
        spans) to the controller; a no-op when both planes are off or
        there is nothing to report.  Returns True when a frame went
        out."""
        from ray_tpu.metrics import exporter as _mexp
        from ray_tpu.metrics import metric_defs as _md

        if self.controller is None or self.controller.closed:
            # reconnect restores it; spans stay in the bounded export
            # queue (overflow there is counted), not drained into a
            # frame that can never be sent
            return False
        payload = _mexp.build_obs_payload(
            self.node_id or "", self.mode, os.getpid()
        )
        if payload is None:
            return False
        try:
            self.controller.send("report_obs", payload)
            _md.inc("rt_obs_frames_sent_total")
        except Exception as e:
            logger.debug("obs frame dropped: %s", e)
            return False
        return True

    def _complete_task(self, result: TaskResult) -> list:
        """Owner-side exactly-once completion (moved to
        core/completion.py with the owner-shard split); returns the
        pending contained-borrow ACK futures the batch ingester awaits
        before confirming `transit_release`."""
        return _completion.complete_task(self, result)

    # ------------------------------------------------------------------
    # get / wait internals (io thread)
    # ------------------------------------------------------------------
    async def _get_one(self, ref: ObjectRef):
        st = self.objects.get(ref.binary())
        if st is not None:
            await st.ready.wait()
            if st.error is not None:
                raise _error_from_envelope(st.error)
            if st.where == _INLINE:
                tag, val = ser.deserialize(memoryview(st.value))
                return _unwrap(tag, val)
            return await self._read_shm(ref, st.node_id)
        return await self._get_borrowed(ref)

    def _deser_pinned(self, id_bytes: bytes, buf):
        """Deserialize a shm buffer; the get's pin is held while the
        value lives.  EVERY get keeps its own store pin: a per-get
        finalizer on the returned array releases exactly that pin when
        the array is garbage-collected (numpy view chains hold base
        references, so the finalizer cannot fire while derived views
        live — the reference releases plasma buffers on value GC the
        same way).  Non-array values may leak extracted views past their
        container's death, so their pin is held for the process lifetime
        (released at shutdown)."""
        import weakref

        import numpy as _np

        tag, val = ser.deserialize(buf)
        out = _unwrap(tag, val)
        if isinstance(out, _np.ndarray):
            weakref.finalize(out, self._release_pin, id_bytes)
        elif (isinstance(out, dict) and out
              and all(isinstance(v, _np.ndarray) for v in out.values())):
            # a column block (dict of arrays, each possibly a zero-copy
            # view into this buffer): release the pin when the LAST
            # array is collected.  The former process-lifetime pin here
            # made every fetched block permanently unspillable, which
            # wedged any shuffle larger than the object store.
            release = self._release_pin
            remaining = [len(out)]

            def _dec(remaining=remaining, release=release,
                     id_bytes=id_bytes):
                remaining[0] -= 1
                if remaining[0] == 0:
                    release(id_bytes)

            for v in out.values():
                weakref.finalize(v, _dec)
        else:
            self._held_pins.add(id_bytes)  # process-lifetime pin
        return out

    def _release_pin(self, id_bytes: bytes):
        if not self._shutdown:
            try:
                self.store.release(id_bytes)
            except Exception as e:
                logger.debug("pin release failed: %s", e)

    def _maybe_verify_local(self, ref: ObjectRef, buf):
        """Opt-in local shm-get verification
        (`object_integrity_verify_get`): compare the buffer against the
        seal-time checksum when one was recorded (driver-put objects).
        Returns the buffer, or None after dropping a corrupt copy so
        the caller treats it as lost.  Off by default — a sealed shm
        segment is not a storage fault domain, and this pays a full
        CRC pass per get."""
        if not self.cfg.object_integrity_verify_get:
            return buf
        st = self.objects.get(ref.binary())
        expected = st.checksum if st is not None else None
        if expected is None:
            return buf
        from ray_tpu.core import integrity as _integrity

        if _integrity.checksum(buf) == expected:
            return buf
        _mdefs.metric("rt_object_integrity_errors_total").inc(
            tags={"path": "get"}
        )
        logger.error(
            "local shm copy of %s failed seal-time checksum; dropping "
            "it and re-deriving", ref.hex()[:12],
        )
        del buf
        self.store.release(ref.binary())
        self.store.delete(ref.binary())
        return None

    async def _read_shm(self, ref: ObjectRef, node_id: Optional[str]):
        try:
            buf = self.store.get(ref.binary(), timeout_ms=0)
            buf = self._maybe_verify_local(ref, buf)
            if buf is None:  # corrupt local copy: treat as lost
                return await self._reconstruct_and_get(ref)
        except ObjectNotFoundError:
            buf = None
            if node_id is not None and node_id != self.node_id:
                try:
                    await self.noded.call(
                        "pull_object",
                        {"id": ref.binary(), "node_id": node_id},
                    )
                    # non-blocking read — a 30s blocking shm wait here
                    # would freeze this whole event loop; if the pulled
                    # copy was re-spilled before we pinned it, the
                    # restore loop below recovers it
                    buf = self.store.get(ref.binary(), timeout_ms=0)
                except (rpc.RemoteError, rpc.RpcError) as e:
                    # a failed pull — source gone, or the copy failed
                    # checksum twice (ObjectCorruptionError) — is
                    # treat-as-lost: re-derive via lineage when this
                    # owner retained it, else surface the failure
                    if ref.binary() not in self.lineage:
                        raise
                    logger.warning(
                        "pull of %s from %s failed (%s); re-deriving "
                        "via lineage", ref.hex()[:12], node_id[:8], e,
                    )
                    return await self._reconstruct_and_get(ref)
                except ObjectNotFoundError:
                    pass  # re-spilled under us: restore loop below
            if buf is None:
                # spilled-to-disk primaries restore without recompute;
                # a restored object can be re-evicted/re-spilled before
                # we read it under sustained pressure, so retry a few
                # times before falling back to lineage reconstruction
                for _attempt in range(3):
                    reply = await self.noded.call(
                        "restore_object", {"id": ref.binary()}
                    )
                    if not (reply and reply.get("ok")):
                        break
                    try:
                        buf = self.store.get(ref.binary(), timeout_ms=0)
                        break
                    except ObjectNotFoundError:
                        await asyncio.sleep(0.1)
                if buf is None:
                    return await self._reconstruct_and_get(ref)
        return self._deser_pinned(ref.binary(), buf)

    async def _prime_borrowed(self, refs):
        """Bulk-resolve foreign-owned refs before the per-ref gather:
        one `get_object_values` frame per owner per 512 refs instead of
        one routed RPC per ref (the object-location fan-in channel —
        `src/ray/pubsub/README.md`).  Failures degrade silently to the
        per-ref path.  Returns the primed ids so the caller can prune
        entries its gather never consumed."""
        groups: Dict[tuple, list] = {}
        primed: list = []
        for r in refs:
            b = r.binary()
            if (r.owner is not None and tuple(r.owner) != self.address
                    and b not in self.objects
                    and b not in self._primed_replies
                    and not self.store.contains(b)):
                groups.setdefault(tuple(r.owner), []).append(b)

        async def _one_chunk(owner, chunk):
            try:
                replies = await self.noded.call("route", {
                    "target": owner,
                    "method": "get_object_values",
                    "payload": {"ids": chunk},
                    "want_reply": True,
                })
            except Exception as e:
                # degraded: per-ref path covers this chunk
                logger.debug("batched owner fetch failed: %s", e)
                return
            for id_b, rep in zip(chunk, replies):
                # not-yet-ready objects come back "pending" so one slow
                # producer can't hold its chunk's reply hostage; the
                # per-ref path (which awaits readiness) handles them
                if rep and rep[0] != "pending":
                    self._primed_replies[id_b] = rep
                    primed.append(id_b)

        chunks = []
        for owner, ids in groups.items():
            if len(ids) < 4:
                continue  # a couple of refs aren't worth a bulk frame
            for i in range(0, len(ids), self._BULK_GET_CHUNK):
                chunks.append(
                    _one_chunk(owner, ids[i:i + self._BULK_GET_CHUNK])
                )
        if chunks:  # all owners, all chunks resolve concurrently
            await asyncio.gather(*chunks)
        return primed

    async def _get_borrowed(self, ref: ObjectRef):
        """Fetch a foreign-owned value.  Loops rather than trusting one
        location answer: between the owner's reply and our read, the
        primary can be re-spilled (and, under storage faults, its disk
        copy quarantined) — each round tries the local store, then a
        daemon restore, then RE-ASKS the owner, whose verify path
        restores or re-derives via lineage before handing out a
        location.  The old single-shot 30s blocking shm wait both froze
        this event loop and hung on primaries nobody would restore."""
        if self.store.contains(ref.binary()):
            buf = self.store.get(ref.binary(), timeout_ms=0)
            return self._deser_pinned(ref.binary(), buf)
        if ref.owner is None:
            raise exc.ObjectLostError(object_id=ref.id)
        reply = self._primed_replies.pop(ref.binary(), None)
        for attempt in range(8):
            if reply is None:
                reply = await self.noded.call(
                    "route",
                    {
                        "target": tuple(ref.owner),
                        "method": "get_object_value",
                        "payload": {"id": ref.binary()},
                        "want_reply": True,
                    },
                )
            kind = reply[0]
            if kind == "inline":
                tag, val = ser.deserialize(memoryview(reply[1]))
                return _unwrap(tag, val)
            if kind == "error":
                raise _error_from_envelope(reply[1])
            if kind != "shm":
                raise exc.ObjectLostError(object_id=ref.id)
            node_id = reply[1]
            reply = None  # a failed round re-asks the owner
            try:
                if (node_id != self.node_id
                        and not self.store.contains(ref.binary())):
                    await self.noded.call(
                        "pull_object",
                        {"id": ref.binary(), "node_id": node_id},
                    )
                buf = self.store.get(ref.binary(), timeout_ms=0)
                return self._deser_pinned(ref.binary(), buf)
            except (ObjectNotFoundError, rpc.RemoteError, rpc.RpcError) as e:
                if node_id == self.node_id:
                    # spilled primary on this node: restore in place.
                    # A restore RPC that itself fails (daemon handler
                    # error, flapping conn — exactly the fault regime
                    # this loop exists for) is a failed ROUND, not an
                    # escape from the retry contract.
                    try:
                        r2 = await self.noded.call(
                            "restore_object", {"id": ref.binary()}
                        )
                    except (rpc.RemoteError, rpc.RpcError) as re2:
                        logger.debug("restore of borrowed %s failed: %s",
                                     ref.hex()[:12], re2)
                        r2 = None
                    if r2 and r2.get("ok"):
                        try:
                            buf = self.store.get(ref.binary(), timeout_ms=0)
                            return self._deser_pinned(ref.binary(), buf)
                        except ObjectNotFoundError:
                            pass  # re-spilled already: next round
                logger.debug(
                    "borrowed %s unavailable at %s (attempt %d): %s",
                    ref.hex()[:12], str(node_id)[:8], attempt + 1, e,
                )
                await asyncio.sleep(
                    backoff_delay_s(attempt, base_s=0.05, cap_s=1.0,
                                    rng=self._retry_rng)
                )
        raise exc.ObjectLostError(
            f"object {ref.hex()} unavailable after 8 fetch rounds "
            "(primary kept vanishing: re-spilled/corrupt faster than "
            "it could be restored or re-derived)",
            object_id=ref.id,
        )

    async def _reconstruct_object(self, ref: ObjectRef):
        """Lineage reconstruction (reference:
        `object_recovery_manager.h:90`): resubmit the creating task and
        wait for the object to exist again (no value read)."""
        spec = self.lineage.get(ref.binary())
        if spec is None:
            raise exc.ObjectLostError(
                f"object {ref.hex()} lost and no lineage retained",
                object_id=ref.id,
            )
        with self._state_lock:
            st = self.objects[ref.binary()]
            # Dedup on the creating task: concurrent reconstructions of
            # this ref (two borrowers racing) or of SIBLING returns of
            # the same task must not double-resubmit.  Worse than the
            # wasted execution: a second resubmit would replace
            # st.ready with a fresh event AFTER the first waiter parked
            # on the old one — completion sets only the current event
            # and the first waiter hangs forever (the bit-flip chaos
            # storm found exactly this wedge).
            already = spec.task_id.binary() in self.pending_tasks
            if st.ready.is_set():
                st.ready = asyncio.Event()
                st.where = None
            # capture under the lock: THIS is the event completion sets
            wait_ev = st.ready
            if not already:
                # the resubmit keeps the spec's retry budget: a worker
                # killed DURING re-derivation (chaos mid-epoch) must
                # retry like any other attempt, not permanently fail
                # the object — the budget still bounds total attempts
                # per resubmission
                self.pending_tasks[spec.task_id.binary()] = _PendingTask(
                    spec, spec.max_retries
                )
                if spec.actor_id is None:
                    # lineage resubmits count as submissions so
                    # per-shard submitted/completed stay balanced
                    # (shard.lock nests inside _state_lock by the
                    # documented order)
                    shard = self._shard_for(spec.task_id.binary())
                    with shard.lock:
                        shard.submitted += 1
                # completion decrements submitted refs again, so
                # re-pin args
                for a in spec.args:
                    if isinstance(a, ArgRef):
                        rc = self.refs.get(a.id_bytes)
                        if rc:
                            rc.submitted += 1
        if not already:
            logger.info("reconstructing %s via lineage resubmit",
                        ref.hex())
            _mdefs.inc("rt_object_reconstructions_total")
            if spec.actor_id is not None:
                # actor-task returns re-execute ON the actor: route
                # through the ordered actor queue with a fresh sequence
                # number (the original seq was consumed; replaying it
                # would wedge the executor's in-order delivery)
                spec.seq_no = next_actor_seq(
                    spec.actor_id.binary(), spec.kwargs.get("__rt_group__")
                )
                self._push_actor_task(spec.actor_id.binary(), spec)
            else:
                self._push_or_queue(spec)
        await wait_ev.wait()
        if st.error is not None:
            raise _error_from_envelope(st.error)
        return st

    async def _reconstruct_and_get(self, ref: ObjectRef):
        st = await self._reconstruct_object(ref)
        if st.where == _INLINE:
            tag, val = ser.deserialize(memoryview(st.value))
            return _unwrap(tag, val)
        return await self._read_shm(ref, st.node_id)

    async def _wait(self, refs, num_returns, timeout):
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds number of refs")
        done_flags = [False] * len(refs)

        # Synchronous readiness scan FIRST: already-ready refs (and the
        # `wait(timeout=0)` poll controllers issue every tick) cost zero
        # task allocations.  Without this, a 1k-ref drain loop
        # (`done, pending = wait(pending, 1)`) re-arms a coroutine per
        # ref per call — O(n^2) task churn across the drain.
        pending_idx: List[int] = []
        for i, r in enumerate(refs):
            st = self.objects.get(r.binary())
            if st is not None:
                if st.ready.is_set():
                    done_flags[i] = True
                else:
                    pending_idx.append(i)
            elif self.store.contains(r.binary()):
                done_flags[i] = True
            else:
                pending_idx.append(i)

        async def _one(i, r):
            st = self.objects.get(r.binary())
            if st is not None:
                await st.ready.wait()
            elif self.store.contains(r.binary()):
                pass
            elif r.owner is not None:
                # borrowed ref: the owner's get_object_value blocks until
                # the object is ready (covers inline objects that never
                # touch the shm store)
                await self.noded.call(
                    "route",
                    {
                        "target": tuple(r.owner),
                        "method": "get_object_value",
                        "payload": {"id": r.binary()},
                        "want_reply": True,
                    },
                )
            else:
                while not self.store.contains(r.binary()):
                    await asyncio.sleep(0.005)
            done_flags[i] = True

        tasks: List[asyncio.Task] = []
        if sum(done_flags) < num_returns and (timeout is None or timeout > 0):
            # waiters only for the refs the scan saw as pending
            tasks = [
                asyncio.create_task(_one(i, refs[i])) for i in pending_idx
            ]
        try:
            deadline = None if timeout is None else time.monotonic() + timeout
            while tasks and sum(done_flags) < num_returns:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    break
                done, pending = await asyncio.wait(
                    tasks, timeout=remaining, return_when=asyncio.FIRST_COMPLETED
                )
                tasks = list(pending)
                if not tasks:
                    break
        finally:
            for t in tasks:
                t.cancel()
        ready = [r for i, r in enumerate(refs) if done_flags[i]]
        not_ready = [r for i, r in enumerate(refs) if not done_flags[i]]
        # the reference's ray.wait contract: done never exceeds
        # num_returns — extra already-ready refs stay in the second list
        # so `done, pending = wait(pending, num_returns=1)` loops
        # consume every result exactly once
        if len(ready) > num_returns:
            not_ready = ready[num_returns:] + not_ready
            ready = ready[:num_returns]
        return ready, not_ready

    # ------------------------------------------------------------------
    # reference counting (reference: reference_count.h:64)
    # ------------------------------------------------------------------
    def _pin_contained(self, captured_refs):
        """Pin owned refs captured inside a serialized value until a
        consumer's borrow registration converts the pin."""
        if not captured_refs:
            return
        with self._state_lock:
            for r in captured_refs:
                if r.owner is not None and tuple(r.owner) == self.address:
                    self.refs.setdefault(r.binary(), _RefCount()).contained = 1

    def _register_contained(self, container_id: bytes, entries, acks=None):
        """The container object `container_id` (a task return we own, or
        a local put) holds references to the listed inner objects.  We
        register a borrow per inner ref on its owner so the inner can't
        be freed while the container lives, and release those borrows
        when the container itself is freed (`_maybe_free`).  Caller
        holds `_state_lock`.  With `acks` (a list), foreign
        registrations become want_reply calls whose futures land there —
        the executor's transit_release must not be sent until the inner
        owners have these borrows on the books."""
        if not entries:
            return
        recorded = []
        foreign: Dict[tuple, list] = {}
        for inner_id, owner in entries:
            owner = tuple(owner)
            if owner == self.address:
                rc = self.refs.setdefault(inner_id, _RefCount())
                # NOTE: rc.contained (the in-flight inline-arg pin) is
                # deliberately untouched — it has its own consumption
                # events (_h_add_borrow / owner deserialization); a
                # container registration is an additional holder, not a
                # consumer
                rc.borrowers += 1
                recorded.append(("selfborrow", inner_id, None))
            else:
                foreign.setdefault(owner, []).append(inner_id)
                recorded.append(("borrow", inner_id, owner))
        # one frame per (owner, 1024-chunk), NOT per inner ref: a task
        # result carrying 10k refs registers in ~10 frames (reference:
        # `src/ray/pubsub/README.md` fan-in argument).  On the ACK'd
        # path one want_reply future covers its whole chunk — the owner
        # replies after processing every event in it.
        for owner, ids in foreign.items():
            for i in range(0, len(ids), self._REF_EVENT_MAX_BATCH):
                chunk = [
                    ("add_borrow", {"id": x, "borrower": self.address})
                    for x in ids[i:i + self._REF_EVENT_MAX_BATCH]
                ]
                try:
                    if acks is not None:
                        acks.append(asyncio.run_coroutine_threadsafe(
                            self.noded.call("route", {
                                "target": owner,
                                "method": "ref_events",
                                "payload": {"events": chunk},
                                "want_reply": True,
                            }), self.loop
                        ))
                    else:
                        for method, p in chunk:
                            self._queue_ref_event(owner, method, p)
                except Exception as e:
                    logger.debug("borrow registration dropped: %s", e)
        if recorded:
            self._contained_in.setdefault(container_id, []).extend(recorded)

    def _release_contained(self, container_id: bytes):
        """Container freed: drop the borrows it held on inner refs.
        Caller holds `_state_lock`."""
        entries = self._contained_in.pop(container_id, None)
        if not entries:
            return
        for kind, inner_id, owner in entries:
            if kind == "selfborrow":
                rc = self.refs.get(inner_id)
                if rc:
                    rc.borrowers -= 1
                    self._maybe_free(inner_id)
            else:
                self._send_remove_borrow(inner_id, owner)

    def _add_local_ref(self, id_bytes: bytes):
        rc = self.refs.setdefault(id_bytes, _RefCount())
        rc.local += 1
        if _RECORD_CALLSITES and not rc.callsite:
            rc.callsite = _creation_site()

    def _maybe_free(self, id_bytes: bytes):
        rc = self.refs.get(id_bytes)
        if rc is None or rc.total() > 0:
            return
        del self.refs[id_bytes]
        # the single deletion point also closes out a registered borrow:
        # every count decrement funnels here, so a borrowed entry can
        # never vanish without its remove_borrow reaching the owner
        if rc.registered and rc.owner_addr:
            self._send_remove_borrow(id_bytes, rc.owner_addr)
        st = self.objects.pop(id_bytes, None)
        spec = self.lineage.pop(id_bytes, None)
        if spec is not None:
            # this object's lineage no longer needs its inputs: release
            # the lineage pins it held on the spec's args (cascades up
            # the chain — freeing a shuffle output unpins its pieces,
            # which unpin the read blocks)
            for a in spec.args:
                if isinstance(a, ArgRef):
                    arc = self.refs.get(a.id_bytes)
                    if arc and arc.lineage > 0:
                        arc.lineage -= 1
                        self._maybe_free(a.id_bytes)
        self._release_contained(id_bytes)
        if st is None:
            self._notify_freed(id_bytes)
            return
        if st.where == _SHM:
            if st.node_id == self.node_id:
                try:
                    self.store.delete(id_bytes)
                except Exception as e:
                    logger.debug("freeing local object: %s", e)
            else:
                try:
                    self.noded.send_threadsafe(
                        "free_remote", {"id": id_bytes, "node_id": st.node_id}
                    )
                except Exception as e:
                    logger.debug("free_remote dropped: %s", e)
        self._notify_freed(id_bytes)

    def _notify_freed(self, id_bytes: bytes):
        """Wake wait_freed() waiters — called at the single deletion
        point (after the local store copy, if any, is gone)."""
        for ev in self._free_waiters.pop(id_bytes, ()):
            ev.set()

    def wait_freed(self, id_bytes: bytes,
                   timeout: Optional[float] = None) -> bool:
        """Event-driven lifetime assertion: block until this process's
        refcount entry for `id_bytes` is retired (and its local shm
        copy deleted), or `timeout` elapses.  Returns True when freed.
        Already-free ids return immediately — tests use this instead of
        wall-clock contains() polling (suite-load deflake).

        When this process holds NO refs entry but the node-shared
        store still has a copy, the deletion will come from ANOTHER
        process's _maybe_free (the owner's) — no local event will ever
        fire, so that case polls the store at a short interval instead
        of registering a dead waiter."""
        import threading as _threading

        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._state_lock:
            if id_bytes not in self.refs:
                if not self.store.contains(id_bytes):
                    return True
                ev = None  # foreign-owned copy: poll below
            else:
                ev = _threading.Event()
                self._free_waiters.setdefault(id_bytes, []).append(ev)
        if ev is None:
            while self.store.contains(id_bytes):
                if deadline is not None and time.monotonic() > deadline:
                    return False
                time.sleep(0.02)
            return True
        freed = ev.wait(timeout)
        if not freed:
            with self._state_lock:
                waiters = self._free_waiters.get(id_bytes)
                if waiters and ev in waiters:
                    waiters.remove(ev)
                    if not waiters:
                        del self._free_waiters[id_bytes]
        return freed

    # ------------------------------------------------------------------
    # kv / controller passthroughs
    # ------------------------------------------------------------------
    def kv_put(self, key: str, value: bytes):
        return self._run(self.controller.call("kv_put", {"key": key, "value": value}))

    def kv_get(self, key: str) -> Optional[bytes]:
        return self._run(self.controller.call("kv_get", {"key": key}))

    def kv_del(self, key: str):
        return self._run(self.controller.call("kv_del", {"key": key}))

    def controller_call(self, method: str, payload=None, timeout=None):
        return self._run(self.controller.call(method, payload), timeout=timeout)

    def noded_call(self, method: str, payload=None, timeout=None):
        return self._run(self.noded.call(method, payload), timeout=timeout)

    # ------------------------------------------------------------------
    # inbound handlers (io thread)
    # ------------------------------------------------------------------
    async def _handle(self, method, payload, conn):
        fn = getattr(self, "_h_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"runtime: no handler {method!r}")
        return await fn(payload, conn)

    async def _h_publish(self, payload, conn):
        """Pubsub delivery from the controller (reference:
        `src/ray/pubsub/` long-poll push): fan the message out to every
        local queue subscribed to its channel."""
        channel = payload.get("channel")
        with self._state_lock:
            queues = list(self._pubsub_queues.get(channel, []))
        for q in queues:
            q.put_nowait(payload.get("msg"))
        return {"ok": True}

    def subscribe(self, channel: str):
        """Subscribe to a controller pubsub channel; returns an
        `asyncio.Queue`-backed iterator handle usable from any thread
        via `next_message(timeout)` (reference: `GcsSubscriber` —
        typed channel subscription with queued delivery)."""
        import queue as _q

        q = _q.Queue()
        if self._shutdown:
            raise RuntimeError("runtime is shut down")
        with self._state_lock:
            self._pubsub_queues.setdefault(channel, []).append(q)
        cancelled = None
        try:
            self._run(self._pubsub_reconcile(), timeout=30)
        except asyncio.CancelledError as e:
            # loop shutdown racing this subscribe: still run the
            # cleanup below (the queue must not stay 'desired'), then
            # surface the cancellation
            cancelled = e
        except Exception as e:
            # judged below by whether registration actually landed
            logger.debug("subscribe attempt errored: %s", e)
        with self._state_lock:
            registered = (
                cancelled is None and channel in self._pubsub_registered
            )
        if not registered:
            with self._state_lock:
                lst = self._pubsub_queues.get(channel, [])
                if q in lst:
                    lst.remove(q)
                if not lst:
                    self._pubsub_queues.pop(channel, None)
            # the RPC may have landed despite the failure (uncertain):
            # a follow-up reconcile unsubscribes anything undesired
            self._spawn_pubsub_reconcile()
            if cancelled is not None:
                raise cancelled
            raise RuntimeError(
                f"pubsub subscribe failed for channel {channel!r}"
            )

        class _Subscription:
            def __init__(self, runtime):
                self._rt = runtime

            def next_message(self, timeout=None):
                return q.get(timeout=timeout)

            def close(self):
                with self._rt._state_lock:
                    lst = self._rt._pubsub_queues.get(channel, [])
                    if q in lst:
                        lst.remove(q)
                    if not lst:
                        # last local watcher gone: desired state no
                        # longer includes the channel; the reconciler
                        # unregisters it at the controller
                        self._rt._pubsub_queues.pop(channel, None)
                # fire-and-forget: close() must not block on a wedged
                # controller, and the reconciler serializes against any
                # concurrent subscribe()
                self._rt._spawn_pubsub_reconcile()

        return _Subscription(self)

    def _spawn_pubsub_reconcile(self) -> None:
        """Fire-and-forget a reconcile pass on the io loop.  The
        coroutine is created INSIDE the loop-thread callback, never
        handed across threads: `run_coroutine_threadsafe` parks the
        coroutine in a callback that silently never runs when the loop
        stops first — abandoning it un-awaited (CPython warns at GC).
        With this shape, a stopped loop simply never creates it."""
        def _cb():
            if self._shutdown:
                return
            task = asyncio.ensure_future(self._pubsub_reconcile())
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception()
            )

        try:
            self.loop.call_soon_threadsafe(_cb)
        except Exception as e:
            # loop closed: nothing to reconcile against anymore
            logger.debug("pubsub reconcile not scheduled: %s", e)

    async def _pubsub_reconcile(self) -> bool:
        """Single-writer pubsub registration reconciler: drives the
        controller-side registration set toward the desired state
        (channels with live local queues).  Every (un)subscribe RPC in
        the process flows through here, serialized by one asyncio lock
        on the io loop — so a close()'s trailing unsubscribe can never
        sever a concurrent subscribe(), and the reconnect path's durable
        resubscribe can't resurrect a channel whose last watcher closed
        (reference: `GcsSubscriber` keeps one registration per channel
        per connection).

        A channel whose RPC outcome is unknown (timeout, or this task
        cancelled mid-RPC — the frame may already be at the controller)
        goes into `_pubsub_uncertain`; the next pass resolves it by
        re-subscribing (idempotent at the controller) when desired or
        unsubscribing (harmless no-op) when not, so a cancelled
        subscribe() can't leave an orphan server-side registration
        pushing into a queueless connection forever.  Failures are
        per-channel: one bad channel never blocks the others.  Returns
        False if any subscribe RPC failed this pass."""
        async with self._pubsub_async_lock:
            failed: set = set()
            while True:
                with self._state_lock:
                    desired = set(self._pubsub_queues)
                    registered = set(self._pubsub_registered)
                    uncertain = set(self._pubsub_uncertain)
                to_add = desired - registered - failed
                to_del = (registered | uncertain) - desired - failed
                if not to_add and not to_del:
                    return not failed
                for ch in sorted(to_add):
                    try:
                        await asyncio.wait_for(
                            self.controller.call(
                                "subscribe", {"channel": ch}
                            ),
                            10,
                        )
                    except asyncio.CancelledError:
                        with self._state_lock:
                            self._pubsub_uncertain.add(ch)
                        raise
                    except Exception:
                        logger.warning(
                            "pubsub subscribe RPC failed for %r", ch,
                            exc_info=True,
                        )
                        with self._state_lock:
                            self._pubsub_uncertain.add(ch)
                        failed.add(ch)
                        continue
                    with self._state_lock:
                        self._pubsub_registered.add(ch)
                        self._pubsub_uncertain.discard(ch)
                for ch in sorted(to_del):
                    # deregister locally FIRST: if a subscribe() lands
                    # mid-RPC the next loop pass re-subscribes, and the
                    # same-connection RPC ordering keeps it after this
                    with self._state_lock:
                        self._pubsub_registered.discard(ch)
                    try:
                        await asyncio.wait_for(
                            self.controller.call(
                                "unsubscribe", {"channel": ch}
                            ),
                            10,
                        )
                    except asyncio.CancelledError:
                        with self._state_lock:
                            self._pubsub_uncertain.add(ch)
                        raise
                    except Exception as e:
                        # best-effort; closed conns get pruned
                        logger.debug("unsubscribe failed: %s", e)
                    # one attempt resolves the uncertainty either way:
                    # a failed unsubscribe on a live conn is rare, and
                    # retrying it forever would spin this pass
                    with self._state_lock:
                        self._pubsub_uncertain.discard(ch)

    async def _h_task_result(self, payload, conn):
        """A task we own finished on a worker (legacy single-result
        frame: daemon relays, worker_died routes) or was routed back via
        the daemons.  Direct executor pushes arrive coalesced as
        `task_result_batch`; both funnel into the same ingestion path
        (core/completion.py)."""
        result: TaskResult = (
            payload["result"] if isinstance(payload, dict) else payload
        )
        await _completion.ingest_results(self, [result], conn)

    async def _h_task_result_batch(self, payload, conn):
        """Coalesced completion frame: every result one executor
        produced for this owner within one connection tick (reference
        analog: the owner-side fan-in that keeps completion dispatch
        O(#frames), not O(#tasks); see docs/control_plane.md)."""
        results = list(payload.results)
        await _completion.ingest_results(self, results, conn)

    async def _h_stream_item(self, payload, conn):
        """One yielded item of a streaming-generator task we own arrived
        (ahead of the final task_result).  Duplicate indices (task retry
        replaying the stream) are dropped — item object ids are
        deterministic in (task_id, index)."""
        tid = payload["task_id"].binary()
        index = payload["index"]
        ret = payload["item"]
        oid = ObjectID.for_return(payload["task_id"], index)
        with self._state_lock:
            stream = self._streams.get(tid)
            if stream is None or oid.binary() in self.objects:
                return
            st = _ObjectState(ready=asyncio.Event())
            if ret[0] == _INLINE:
                st.where, st.value, st.size = _INLINE, ret[1], len(ret[1])
                contained = ret[2] if len(ret) > 2 else None
            else:
                st.where, st.node_id, st.size = _SHM, ret[1], ret[2]
                contained = ret[3] if len(ret) > 3 else None
            if contained:
                # acks parked per task: _h_task_result awaits them before
                # confirming transit_release, so streamed items get the
                # same registered-before-release guarantee as returns
                acks = self._stream_reg_acks.setdefault(tid, [])
                self._register_contained(oid.binary(), contained, acks)
            st.ready.set()
            self.objects[oid.binary()] = st
            self._add_local_ref(oid.binary())
            stream.items[index] = ObjectRef(oid, self.address, _register=True)
        stream.event.set()

    def stream_next(self, task_id_bytes: bytes, timeout: Optional[float] = None):
        """Next item ObjectRef of a streaming task, blocking.  Returns
        None when the stream is exhausted; raises the task's error at
        the position it occurred."""
        return self._run(
            self._stream_next_async(task_id_bytes), timeout=timeout
        )

    async def stream_wait_done(self, tid: bytes, trace_ctx=None):
        """Await completion of a streaming task (ok or error); used by
        watchers (e.g. serve's router queue-len tracking) that must not
        race the consumer.  Returns the stream's terminal error envelope
        (None on clean completion) — read off the held stream object, so
        a consumer popping the stream can't hide the error from the
        watcher (the router's breaker classification depends on it).

        `trace_ctx` is the watched request's trace context: the
        stream's terminal event is recorded into THAT trace, so a
        streaming request's lifecycle stays one trace id end to end
        instead of fragmenting at the watcher."""
        with self._state_lock:
            stream = self._streams.get(tid)
        if stream is None:
            return None
        await stream.done.wait()
        if trace_ctx is not None:
            from ray_tpu.util import tracing as _tracing

            if stream.error is not None:
                _tracing.record_instant("stream_done", trace_ctx,
                                        error=True)
            else:
                _tracing.record_instant("stream_done", trace_ctx)
        return stream.error

    async def _stream_next_async(self, tid: bytes):
        while True:
            with self._state_lock:
                stream = self._streams.get(tid)
                if stream is None:
                    return None
                nxt = stream.items.pop(stream.consumed + 1, None)
                if nxt is not None:
                    stream.consumed += 1
                    return nxt
                if stream.total is not None and stream.consumed >= stream.total:
                    self._streams.pop(tid, None)
                    return None
                if stream.error is not None:
                    # the next in-order item will never arrive: surface
                    # the error (delivered items were consumed above)
                    self._streams.pop(tid, None)
                    raise _error_from_envelope(stream.error)
                stream.event.clear()
            await stream.event.wait()

    def stream_release(self, tid: bytes):
        """Drop a stream's owner-side state (abandoned consumer).
        Unconsumed item refs are released by their ObjectRefs' GC; items
        still arriving find no stream and are ignored.  Completion
        watchers (stream_wait_done) are woken — the stream is finished
        as far as this owner is concerned.  If the producer is still
        running, it is told to stop (an unbounded generator must not
        keep pinning its worker and sealing orphaned items into shm)."""
        with self._state_lock:
            stream = self._streams.pop(tid, None)
            pt = self.pending_tasks.get(tid)
        if stream is None or self._shutdown:
            return
        try:
            self.loop.call_soon_threadsafe(stream.done.set)
            if pt is not None:
                asyncio.run_coroutine_threadsafe(
                    self._stream_cancel_remote(tid, pt.spec), self.loop
                )
        except RuntimeError:
            pass

    async def _stream_cancel_remote(self, task_id: bytes, spec: TaskSpec):
        """Best-effort 'stop producing' to wherever the streaming task
        runs (same transport walk as _cancel_remote)."""
        conns = []
        for shard in self._shards:
            with shard.lock:
                for pool, lease in shard.conn_lease.values():
                    if task_id in lease.assigned:
                        conns.append(lease.conn)
        if spec.actor_id is not None:
            with self._state_lock:
                c = self._actor_conns.get(spec.actor_id.binary())
            if c is not None:
                conns.append(c)
        for conn in conns:
            try:
                # threadsafe variant: the conn may live on a shard loop
                conn.send_threadsafe("stream_cancel", {"task_id": task_id})
                return
            except Exception as e:
                logger.debug("stream_cancel to executor failed: %s", e)
        try:
            self.noded.send("stream_cancel", {"task_id": task_id})
        except Exception as e:
            logger.debug("stream_cancel via noded failed: %s", e)

    async def _h_stream_cancel(self, payload, conn):
        """Executor side: mark the stream abandoned; _stream_out stops
        at the next yield boundary and closes the user generator."""
        cancelled = self._cancelled_streams = getattr(
            self, "_cancelled_streams", set()
        )
        cancelled.add(payload["task_id"])

    async def _verify_shm_primary(self, id_bytes: bytes, st):
        """A borrower is about to be pointed at our shm primary: make
        sure it still exists.  Evicted/lost primaries restore from
        spill or rebuild via lineage BEFORE the location is handed out —
        this is what makes chained reconstruction work (rebuilding task
        B pulls arg A through this path, and A may itself be gone)."""
        if st.node_id != self.node_id or self.store.contains(id_bytes):
            return st
        ref = ObjectRef(ObjectID(id_bytes), self.address)
        try:
            # restore from spill, else rebuild via lineage — WITHOUT
            # deserializing the value (no get-pin, no wasted decode)
            reply = await self.noded.call("restore_object", {"id": id_bytes})
            if not (
                reply and reply.get("ok") and self.store.contains(id_bytes)
            ):
                await self._reconstruct_object(ref)
        except Exception as e:
            logger.warning("could not restore %s for borrower: %r",
                           ref.hex(), e, exc_info=True)
        return self.objects.get(id_bytes) or st

    async def _h_get_object_value(self, payload, conn):
        st = self.objects.get(payload["id"])
        if st is None:
            return ("gone",)
        await st.ready.wait()
        if st.error is not None:
            return ("error", st.error)
        if st.where == _INLINE:
            return ("inline", st.value)
        st = await self._verify_shm_primary(payload["id"], st)
        if st.error is not None:
            return ("error", st.error)
        if st.where == _INLINE:  # reconstruction may have inlined it
            return ("inline", st.value)
        return ("shm", st.node_id)

    async def _h_get_object_values(self, payload, conn):
        """Bulk location/value lookup: one routed frame resolves a whole
        batch of this owner's objects for a borrower's multi-ref get
        (reference: the object-location pubsub channel's fan-in
        argument, `src/ray/pubsub/README.md` — a 10k-ref get must not
        be 10k waiting RPCs)."""
        out = []
        for i in payload["ids"]:
            st = self.objects.get(i)
            if st is None or not st.ready.is_set():
                # don't hold the whole batch for one slow producer —
                # the caller's per-ref path awaits readiness itself
                out.append(("pending",))
            else:
                out.append(await self._h_get_object_value({"id": i}, conn))
        return out

    async def _h_add_borrow(self, payload, conn):
        """Owner side: a borrower registered (reference: the owner's
        borrower set, `reference_count.h:64`).  The reply doubles as the
        registration ACK workers await before sending a task result that
        forwards the ref onward."""
        with self._state_lock:
            rc = self.refs.setdefault(payload["id"], _RefCount())
            rc.borrowers += 1
            b = payload.get("borrower")
            if b is not None:
                b = tuple(b)
                rc.borrower_addrs[b] = rc.borrower_addrs.get(b, 0) + 1
            rc.contained = 0  # pin transfers to the borrower
        return {"ok": True}

    async def _h_ref_events(self, payload, conn):
        """Owner side of the coalesced ref-event channel: one frame
        carries a whole batch of borrow registrations/releases from one
        counterpart (reference: `src/ray/pubsub/README.md` — reducing
        O(#objects) waiting RPCs to O(#subscribers))."""
        for method, p in payload["events"]:
            if method == "add_borrow":
                await self._h_add_borrow(p, conn)
            elif method == "remove_borrow":
                await self._h_remove_borrow(p, conn)

    async def _h_remove_borrow(self, payload, conn):
        with self._state_lock:
            rc = self.refs.get(payload["id"])
            if rc:
                b = payload.get("borrower")
                if b is not None:
                    b = tuple(b)
                    n = rc.borrower_addrs.get(b, 0)
                    if n <= 0:
                        # no matching registration from this borrower (its
                        # add_borrow was lost en route): rejecting the
                        # unmatched remove keeps the count from going
                        # negative and freeing under live borrowers
                        return
                    if n == 1:
                        rc.borrower_addrs.pop(b, None)
                    else:
                        rc.borrower_addrs[b] = n - 1
                rc.borrowers -= 1
                self._maybe_free(payload["id"])

    async def _h_worker_log(self, payload, conn):
        """Driver side: task/actor print lines from a worker (reference:
        `log_monitor.py:103` republishing worker logs to the driver)."""
        if not self.cfg.log_to_driver:
            return
        name = payload.get("name", "?")
        pid = payload.get("pid", 0)
        stream = payload.get("stream", "out")
        out = sys.stderr
        for line in payload.get("lines") or ():
            self._worker_log_lines.append((name, pid, stream, line))
            try:
                out.write(f"({name} pid={pid}) {line}\n")
            except (OSError, ValueError):
                return  # driver stdout closed/redirected away
        try:
            out.flush()
        except (OSError, ValueError):
            pass  # driver stdout closed/redirected away

    async def _h_transit_release(self, payload, conn):
        """The owner of a task's returns has registered its contained
        borrows with every inner owner: this executor's transit pins on
        the forwarded refs can drop."""
        entries = self._return_transit.pop(payload["task_id"], None)
        if entries:
            with self._state_lock:
                self._release_transit(entries)

    async def _h_memory_summary(self, payload, conn):
        """This process's object-reference table for `rt memory`
        (reference: `ray memory` — `_private/internal_api.py:34`
        memory_summary over every worker's reference table +
        `scripts.py:1955`).  One row per live ref entry: what kind of
        hold this process has, the value's residence, and (opt-in) the
        creation callsite."""
        rows = []
        with self._state_lock:
            for id_b, rc in self.refs.items():
                st = self.objects.get(id_b)
                if st is not None:
                    kind = "owned"
                elif rc.registered:
                    kind = "borrowed"
                else:
                    kind = "pending"  # counted but neither owned nor
                    #                   registered (e.g. pure transit)
                rows.append({
                    "object_id": id_b.hex(),
                    "kind": kind,
                    "local": rc.local,
                    "submitted": rc.submitted,
                    "borrowers": rc.borrowers,
                    "contained": rc.contained,
                    "transit": rc.transit,
                    "lineage_pinned": id_b in self.lineage,
                    "size": st.size if st else None,
                    "where": st.where if st else None,
                    "node_id": st.node_id if st else None,
                    "owner": ("self" if kind == "owned" else
                              list(rc.owner_addr) if rc.owner_addr
                              else None),
                    "borrower_addrs": [
                        [list(a), n] for a, n in rc.borrower_addrs.items()
                    ],
                    "callsite": rc.callsite,
                })
            held_pins = len(self._held_pins)
        return {
            "address": list(self.address),
            "mode": self.mode,
            "pid": os.getpid(),
            "held_pins": held_pins,
            "refs": rows,
        }

    async def _h_ping(self, payload, conn):
        return "pong"

    async def _h_dump_stacks(self, payload, conn):
        """All-thread stack dump for the on-demand profiler (reference:
        py-spy dump via `profile_manager.py:78`; this is the in-process
        fallback that needs no native tooling)."""
        from ray_tpu.util.profiling import dump_all_stacks

        return dump_all_stacks()

    async def _h_profile_cpu(self, payload, conn):
        """Sampled CPU flamegraph of this worker (reference: py-spy
        record --format flamegraph): folded stacks over a window, run
        off-loop so sampling never blocks task execution."""
        from ray_tpu.util.profiling import sample_flamegraph

        duration = min(float((payload or {}).get("duration_s", 5.0)), 60.0)
        hz = min(float((payload or {}).get("hz", 99.0)), 500.0)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: sample_flamegraph(duration, hz)
        )

    async def _h_profile_memory(self, payload, conn):
        """Windowed allocation profile (reference: memray heap
        profiles): stdlib tracemalloc diff over a window, off-loop."""
        from ray_tpu.util.profiling import memory_profile

        duration = min(float((payload or {}).get("duration_s", 5.0)), 60.0)
        top = int((payload or {}).get("top", 30))
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: memory_profile(duration, top)
        )

    async def _h_set_accel_env(self, payload, conn):
        """Daemon push at lease-grant time: accelerator isolation env
        (TPU_VISIBLE_CHIPS et al — `core/accelerators.py`).  Must land
        before user code first initializes the ML framework; the daemon
        sends it on the same ordered stream as the task push.  An empty
        string unsets the variable (all-chip grants clear restrictions).
        """
        import sys as _sys

        changed = False
        for k, v in (payload or {}).items():
            if v == "":
                if k in os.environ:
                    del os.environ[k]
                    changed = True
            elif os.environ.get(k) != v:
                os.environ[k] = v
                changed = True
        if changed and "jax" in _sys.modules:
            logger.warning(
                "accelerator env changed after jax was imported; the new "
                "chip visibility takes effect only in a fresh worker"
            )
        return {"ok": True}

    # ---- executor side ----------------------------------------------
    async def _h_execute_task(self, spec: TaskSpec, conn):
        if spec.actor_id is not None:
            await self._exec_actor_ordered(spec, conn)
        else:
            asyncio.ensure_future(self._exec_task(spec, conn))

    async def _h_create_actor_instance(self, aspec: ActorCreationSpec, conn):
        if aspec.runtime_env:
            # plugin-ordered application (env_vars, working_dir,
            # py_modules, pip, custom) BEFORE the class blob
            # deserializes — the pickle may import shipped modules
            from ray_tpu.core.runtime_env import apply_runtime_env

            await apply_runtime_env(aspec.runtime_env, self)
        cls = ser.loads(aspec.class_blob)
        self.actor_id = aspec.actor_id
        self._actor_aspec = aspec
        groups = dict(aspec.concurrency_groups or {})
        # per-group execution lanes (reference:
        # `concurrency_group_manager.h`): each named group gets its OWN
        # thread pool (sync methods) and a concurrency cap enforced by
        # a single-consumer lane queue — dedicated pools mean a flooded
        # default lane can never starve a group lane's threads, and the
        # one-acquirer-per-lane queue gives FIFO start order without
        # depending on asyncio.Semaphore waiter fairness.
        self._group_limits: Dict[Optional[str], int] = dict(groups)
        self._group_pools = {
            g: ThreadPoolExecutor(max_workers=n) for g, n in groups.items()
        }
        # default-lane limit: SYNC actors keep max_concurrency even in
        # out-of-order mode (order relaxed, concurrency kept).  Truly
        # async actors keep the historical unbounded default lane —
        # capping it at max_concurrency=1 would introduce exactly the
        # head-of-line blocking these modes exist to remove.
        if (groups or aspec.allow_out_of_order) \
                and not aspec.has_async_methods:
            self._group_limits[None] = aspec.max_concurrency
        self._lane_queues: Dict[Optional[str], asyncio.Queue] = {}
        if aspec.max_concurrency > 1:
            self._exec_pool = ThreadPoolExecutor(
                max_workers=aspec.max_concurrency
            )
        args = [await self._materialize_arg(a) for a in aspec.init_args]
        kwargs = {
            k: await self._materialize_arg(v) for k, v in aspec.init_kwargs.items()
        }
        loop = asyncio.get_running_loop()

        def _make():
            inst = cls.__new__(cls)
            if hasattr(inst, "__init__"):
                inst.__init__(*args, **kwargs)
            return inst

        self.actor_instance = await loop.run_in_executor(self._exec_pool, _make)
        # borrows registered while deserializing init args must be ACKed
        # before this reply: the driver's create-reply releases its
        # init-arg transit pins (same ordering guarantee as task results)
        await self._await_borrow_acks()
        return {"ok": True}

    async def _exec_actor_ordered(self, spec: TaskSpec, conn):
        group = spec.kwargs.get("__rt_group__")
        limits = getattr(self, "_group_limits", None) or {}
        if group is not None and group not in limits:
            envelope = ser.serialize_to_bytes(
                ValueError(
                    f"actor declares no concurrency group {group!r}"
                ),
                tag=ser.TAG_ERROR,
            )
            conn.send("task_result", {
                "result": TaskResult(task_id=spec.task_id, status="error",
                                     error=envelope),
                "owner": spec.owner,
            })
            return
        aspec = self._actor_aspec
        if aspec is not None and aspec.allow_out_of_order:
            # opt-in unordered mode (reference:
            # `out_of_order_actor_scheduling_queue.h:37`): execute as
            # delivered — no seq buffer, so a slow earlier call can
            # never delay a later one
            self._lane_dispatch(group, spec, conn)
            return
        # per-(caller, group) ordered streams: each group is its own
        # sequence lane, so a blocked "io" call never stalls "compute"
        caller = spec.owner[1]
        key = (caller, group)
        # Baseline 0 (a fresh handle's first seq), NOT first-arrival:
        # under transport reordering the first frame to ARRIVE can be a
        # later seq, and a first-arrival baseline would misread the
        # earlier seqs as stale retries and run them out of order
        # (reference: `actor_scheduling_queue.cc` buffers out-of-order
        # arrivals by seq_no for exactly this reason).  Sequence numbers
        # consumed by a PREVIOUS actor incarnation never arrive; the gap
        # timer in _drain_actor_seq skips past them after a bounded wait.
        expect = self._actor_seq_expect.setdefault(key, 0)
        if spec.seq_no < expect:
            if (self._actor_dispatched.get(spec.task_id.binary())
                    == getattr(conn, "serial", None)):
                # duplicate DELIVERY of a call already dispatched from
                # THIS connection (an at-least-once transport replaying
                # a frame): executing it again would repeat its side
                # effects — e.g. pop a second block from a split
                # coordinator that is then never acked.  Drop it; the
                # original's reply rides this same live stream.  The
                # same task id arriving on a NEW conn is a reconnect
                # retry (the original result died with the old conn)
                # and falls through to re-execution.
                logger.debug("dropping duplicate actor call %s (seq %d)",
                             spec.task_id.hex()[:12], spec.seq_no)
                return
            # late retry of an already-superseded sequence number:
            # execute out-of-band (restart relaxes exactly-once ordering,
            # same as the reference with max_task_retries > 0)
            self._record_dispatched(spec, conn)
            self._lane_dispatch(group, spec, conn)
            return
        buf = self._actor_seq_buffer.setdefault(key, {})
        buf[spec.seq_no] = (spec, conn)
        await self._drain_actor_seq(key, group)

    # How long a sequence gap may stall a lane before it is declared a
    # previous-incarnation hole and skipped (transport reorder fills
    # gaps in milliseconds; only restart holes persist this long).
    # Tunable via RT_ACTOR_SEQ_GAP_S: on links whose delays can exceed
    # it, raise the window — a skip on a merely-slow frame relaxes the
    # lane to out-of-order delivery for that frame (logged when it
    # happens).
    _ACTOR_SEQ_GAP_S = float(os.environ.get("RT_ACTOR_SEQ_GAP_S", "1.0"))

    async def _drain_actor_seq(self, key: tuple, group: Optional[str]):
        aspec = self._actor_aspec
        buf = self._actor_seq_buffer.get(key, {})
        if self._actor_drain_lock is None:
            self._actor_drain_lock = asyncio.Lock()
        async with self._actor_drain_lock:
            while self._actor_seq_expect[key] in buf:
                s, c = buf.pop(self._actor_seq_expect[key])
                self._actor_seq_expect[key] += 1
                self._record_dispatched(s, c)
                if aspec is not None and aspec.is_async:
                    self._lane_dispatch(group, s, c)
                else:
                    await self._exec_task(s, c)
        if not buf:
            return
        snapshot = self._actor_seq_expect[key]
        existing = self._actor_seq_timers.get(key)
        if existing is not None:
            if existing[1] == snapshot:
                return  # an up-to-date timer is already pending
            existing[0].cancel()  # stale window: restart it at the new expect

        def _gap_fire():
            self._actor_seq_timers.pop(key, None)
            b = self._actor_seq_buffer.get(key)
            if b and self._actor_seq_expect.get(key) == snapshot:
                # nothing filled the gap within the window: those
                # seqs were consumed by a previous incarnation
                logger.warning(
                    "actor seq lane %s: skipping gap %d->%d after "
                    "%.1fs (previous-incarnation hole, or a frame "
                    "delayed past RT_ACTOR_SEQ_GAP_S)",
                    key, snapshot, min(b), self._ACTOR_SEQ_GAP_S,
                )
                self._actor_seq_expect[key] = min(b)
                asyncio.ensure_future(
                    self._drain_actor_seq(key, group)
                )

        self._actor_seq_timers[key] = (
            self.loop.call_later(self._ACTOR_SEQ_GAP_S, _gap_fire),
            snapshot,
        )

    _DISPATCHED_FENCE_CAP = 8192

    def _record_dispatched(self, spec: TaskSpec, conn):
        """Remember a dispatched actor task id and its origin conn
        (bounded FIFO) so a replayed delivery of the same frame on the
        same connection can be recognized and dropped instead of
        re-executed (duplicate side effects)."""
        tid = spec.task_id.binary()
        if tid not in self._actor_dispatched:
            self._actor_dispatched_order.append(tid)
        self._actor_dispatched[tid] = getattr(conn, "serial", None)
        while len(self._actor_dispatched_order) > self._DISPATCHED_FENCE_CAP:
            self._actor_dispatched.pop(
                self._actor_dispatched_order.popleft(), None
            )

    def _lane_dispatch(self, group: Optional[str], spec: TaskSpec, conn):
        """Enqueue one actor task on its lane.  Each lane has a single
        consumer coroutine, so starts are FIFO in enqueue order and the
        lane's concurrency cap needs no fair semaphore.  A lane with no
        limit (the async default lane) dispatches straight through —
        the historical unbounded path."""
        limits = getattr(self, "_group_limits", None) or {}
        limit = limits.get(group)
        if limit is None:
            asyncio.ensure_future(self._exec_task(spec, conn))
            return
        q = self._lane_queues.get(group)
        if q is None:
            q = self._lane_queues[group] = asyncio.Queue()
            asyncio.ensure_future(self._lane_worker(group, q, limit))
        q.put_nowait((spec, conn))

    async def _lane_worker(self, group: Optional[str], q: asyncio.Queue,
                           limit: int):
        """Single consumer of one lane's queue: admits up to `limit`
        concurrent tasks, in FIFO order."""
        slots = asyncio.Semaphore(limit)
        while True:
            spec, conn = await q.get()
            # only this coroutine acquires, so no barging is possible
            await slots.acquire()
            task = asyncio.ensure_future(self._exec_task(spec, conn))
            task.add_done_callback(lambda _t: slots.release())

    async def _adopt_driver_sys_path(self) -> bool:
        """Extend sys.path from the KV-published driver path (set by
        joining drivers whose spawn-env never reached this worker);
        True when anything new was added — the caller retries its
        deserialization once."""
        import json as _json

        from ray_tpu.core.env_utils import adopt_sys_path

        try:
            blob = await self.controller.call(
                "kv_get", {"key": "driver:sys_path"}
            )
        except Exception as e:
            logger.debug("driver sys_path fetch failed: %s", e)
            return False
        if not blob:
            return False
        return adopt_sys_path(_json.loads(blob))

    def _try_pin_args(self, entries):
        """Phase 2 fast pass: pin every store-resident ArgRef in one
        atomic sweep.  Returns a value list (store-backed args
        deserialized, everything else `_UNRESOLVED` for the caller to
        resolve through `_materialize_arg`), or None when any needed
        object is not immediately pinnable — in which case every pin
        taken this round has been released and the caller re-runs
        phase 1."""
        pinned = []  # (index, id_bytes, buf)
        out = [_UNRESOLVED] * len(entries)

        def _release_all():
            for _i, b, buf in pinned:
                del buf
                try:
                    self.store.release(b)
                except Exception as e:
                    logger.debug("fast-pass pin release failed: %s", e)
            del pinned[:]

        try:
            for i, a in enumerate(entries):
                if not isinstance(a, ArgRef):
                    continue
                b = a.id_bytes
                st = self.objects.get(b)
                if st is not None:
                    if not st.ready.is_set():
                        _release_all()
                        return None
                    if st.error is not None:
                        raise _error_from_envelope(st.error)
                    if st.where == _INLINE:
                        continue  # _materialize_arg: no store access
                else:
                    reply = self._primed_replies.get(b)
                    if reply is not None and reply[0] == "error":
                        raise _error_from_envelope(reply[1])
                    if reply is not None and reply[0] == "inline":
                        continue
                try:
                    buf = self.store.get(b, timeout_ms=0)
                except ObjectNotFoundError:  # not resident right now
                    _release_all()
                    return None
                if st is not None:
                    ref = ObjectRef(ObjectID(b), a.owner)
                    buf = self._maybe_verify_local(ref, buf)
                    if buf is None:  # corrupt copy dropped: re-derive
                        _release_all()
                        return None
                pinned.append((i, b, buf))
        except BaseException:
            _release_all()
            raise
        for i, b, buf in pinned:
            out[i] = self._deser_pinned(b, buf)
        return out

    async def _prefetch_arg(self, a):
        """Phase 1 of task-arg materialization: make the arg's bytes
        LOCAL without taking a store pin (reference: the pull manager
        stages dependencies into plasma unpinned; pinning happens at
        execution).  A task parked here — waiting for a restore or a
        lineage re-derivation of one arg — holds ZERO pins, so its
        other args stay spillable and producers can always write their
        returns.  The old single-phase materialize pinned args as it
        went: under storage faults, a store full of parked consumers'
        pins deadlocked the very re-derivations they waited on."""
        if not isinstance(a, ArgRef):
            return
        ref = ObjectRef(ObjectID(a.id_bytes), a.owner)
        b = ref.binary()
        st = self.objects.get(b)
        if st is not None:  # owned object
            await st.ready.wait()
            if st.error is not None or st.where == _INLINE:
                return
            if self.store.contains(b):
                return
            if st.node_id is not None and st.node_id != self.node_id:
                try:
                    await self.noded.call(
                        "pull_object", {"id": b, "node_id": st.node_id}
                    )
                    return
                except (rpc.RemoteError, rpc.RpcError) as e:
                    logger.debug("prefetch pull of %s failed: %s",
                                 ref.hex()[:12], e)
            reply = await self.noded.call("restore_object", {"id": b})
            if not (reply and reply.get("ok")):
                # lost: re-derive now (no value read) so phase 2 finds
                # it resident
                await self._reconstruct_object(ref)
            return
        # borrowed: ask the owner (whose verify path restores or
        # re-derives before handing out a location), then localize
        if self.store.contains(b):
            return
        if ref.owner is None:
            return  # phase 2 raises the typed error
        for attempt in range(4):
            reply = self._primed_replies.pop(b, None)
            if reply is None:
                reply = await self.noded.call("route", {
                    "target": tuple(ref.owner),
                    "method": "get_object_value",
                    "payload": {"id": b},
                    "want_reply": True,
                })
            kind = reply[0]
            if kind in ("inline", "error"):
                # stash for phase 2 (no bytes in the store to localize)
                self._primed_replies[b] = reply
                return
            if kind != "shm":
                return
            node_id = reply[1]
            if node_id != self.node_id:
                try:
                    await self.noded.call(
                        "pull_object", {"id": b, "node_id": node_id}
                    )
                except (rpc.RemoteError, rpc.RpcError) as e:
                    logger.debug("prefetch pull of borrowed %s: %s",
                                 ref.hex()[:12], e)
            if self.store.contains(b):
                return
            r2 = await self.noded.call("restore_object", {"id": b})
            if r2 and r2.get("ok") and self.store.contains(b):
                return
            await asyncio.sleep(
                backoff_delay_s(attempt, base_s=0.05, cap_s=0.5,
                                rng=self._retry_rng)
            )
        return  # phase 2's own retry loop takes it from here

    async def _materialize_arg(self, a):
        if isinstance(a, tuple) and len(a) == 2 and a[0] == "__rt_inline__":
            try:
                tag, val = ser.deserialize(memoryview(a[1]))
            except ModuleNotFoundError:
                if not await self._adopt_driver_sys_path():
                    raise
                tag, val = ser.deserialize(memoryview(a[1]))
            return _unwrap(tag, val)
        if isinstance(a, ArgRef):
            ref = ObjectRef(ObjectID(a.id_bytes), a.owner)
            return await self._get_one(ref)
        return a

    async def _exec_task(self, spec: TaskSpec, conn):
        t0 = time.time()
        tid = spec.task_id.binary()
        cancelled = getattr(self, "_cancelled_tasks", None)
        if cancelled and tid in cancelled:
            cancelled.discard(tid)
            envelope = ser.serialize_to_bytes(
                exc.TaskCancelledError(task_id=spec.task_id),
                tag=ser.TAG_ERROR,
            )
            conn.send("task_result", {
                "result": TaskResult(task_id=spec.task_id, status="error",
                                     error=envelope),
                "owner": spec.owner,
            })
            return
        if spec.deadline_expired():
            # the caller's budget is spent (the wire re-anchored the
            # remaining budget to this clock): reply the typed error
            # without running work nobody is waiting for
            envelope = ser.serialize_to_bytes(
                exc.DeadlineExceededError(
                    f"task {spec.name!r} deadline expired before execution"
                ),
                tag=ser.TAG_ERROR,
            )
            conn.send("task_result", {
                "result": TaskResult(task_id=spec.task_id, status="error",
                                     error=envelope),
                "owner": spec.owner,
            })
            return
        started = getattr(self, "_started_tasks", None)
        if started is None:
            started = self._started_tasks = set()
        started.add(tid)
        # (discarded in the finally below — the set only guards the
        # not-yet-started window against late cancellation)
        self.task_events.record(
            spec.task_id.binary(), spec.name, "RUNNING",
            node_id=self.node_id, worker_id=self.worker_id.hex(),
        )
        try:
            if spec.runtime_env:
                # applied once; the daemon dedicates this worker to the
                # env hash so a mismatch means a scheduling bug
                if self._applied_env_hash is None:
                    from ray_tpu.core.runtime_env import apply_runtime_env

                    await apply_runtime_env(spec.runtime_env, self)
                    self._applied_env_hash = spec.env_hash
                elif self._applied_env_hash != spec.env_hash:
                    raise exc.RayTpuError(
                        "worker already dedicated to a different "
                        "runtime_env (scheduling bug)"
                    )
            fn = await self._load_function(spec)

            async def _materialize_all():
                # Two-phase, all-or-nothing materialization.  Phase 1
                # localizes every arg WITHOUT pinning; phase 2 pins the
                # whole set atomically — a round that finds any arg
                # missing releases every pin it took and goes back to
                # phase 1.  A task waiting on a restore or a lineage
                # re-derivation therefore holds ZERO pins: its sibling
                # args stay spillable and producers can always write.
                # (Pinning as-you-go deadlocked under storage faults:
                # parked consumers' pins filled the store against the
                # very re-derivations they waited on.)
                kw_items = [(k, v) for k, v in spec.kwargs.items()
                            if not k.startswith("__rt_")]
                entries = list(spec.args) + [v for _, v in kw_items]
                vals = None
                for round_ in range(6):
                    for a in entries:
                        await self._prefetch_arg(a)
                    vals = self._try_pin_args(entries)
                    if vals is not None:
                        break
                    await asyncio.sleep(
                        backoff_delay_s(round_, base_s=0.02, cap_s=0.2,
                                        rng=self._retry_rng)
                    )
                if vals is None:
                    # liveness fallback: the store is churning faster
                    # than a fast pass can pin — take the original
                    # blocking path (pins as it goes)
                    vals = [await self._materialize_arg(a)
                            for a in entries]
                else:
                    # non-pinned entries (inline blobs, plain values,
                    # primed replies) resolve through the normal path —
                    # none of these can stall on the store
                    for i, v in enumerate(vals):
                        if v is _UNRESOLVED:
                            vals[i] = await self._materialize_arg(
                                entries[i]
                            )
                args = vals[: len(spec.args)]
                kwargs = {
                    k: v for (k, _), v in zip(kw_items,
                                              vals[len(spec.args):])
                }
                return args, kwargs

            # blocked-aware: arg resolution stalled on an object that
            # must be restored/re-derived first releases this worker's
            # lease CPUs (same protocol as a parked in-task get) —
            # otherwise every slot can fill with tasks waiting on
            # objects only QUEUED tasks can produce, and lineage
            # reconstruction deadlocks against its own consumers
            args, kwargs = await self._await_blocking_aware(
                _materialize_all()
            )
            loop = asyncio.get_running_loop()
            self._task_local.task_id = spec.task_id
            # ambient deadline: nested .remote() calls made by the user
            # code inherit the parent's remaining budget.  Overwrite by
            # design — every task sets it at start (even to None), so a
            # reset token would only restore a NEIGHBOR's budget.
            _ambient_deadline.set(spec.deadline_s)  # rtlint: disable=RT006

            from ray_tpu.util import tracing as _tracing

            trace_ctx = getattr(spec, "trace_ctx", None)
            if spec.actor_id is not None:
                mname = spec.kwargs["__rt_method__"]
                if mname == "__rt_dag_exec_loop__":
                    # framework-reserved: resident exec loop of a
                    # compiled DAG (dag/execution.py) hosted by this
                    # actor — not a method of the user class
                    import functools

                    from ray_tpu.dag.execution import dag_exec_loop

                    method = functools.partial(
                        dag_exec_loop, self.actor_instance
                    )
                else:
                    method = getattr(self.actor_instance, mname)
                if asyncio.iscoroutinefunction(method):
                    from ray_tpu.core.log_stream import log_ctx_var

                    _log_tok = log_ctx_var.set((spec.owner, spec.name))
                    try:
                        with _tracing.execution_span(spec.name, trace_ctx):
                            value = await method(*args, **kwargs)
                    finally:
                        try:
                            sys.stdout.flush()
                            sys.stderr.flush()
                        except (OSError, ValueError):
                            pass  # stream closed mid-teardown
                        log_ctx_var.reset(_log_tok)
                else:

                    def _call_method():
                        from ray_tpu.core.log_stream import log_ctx_var

                        self._task_local.task_id = spec.task_id
                        # overwrite-by-design: see the async path above
                        _ambient_deadline.set(spec.deadline_s)  # rtlint: disable=RT006
                        _log_tok = log_ctx_var.set((spec.owner, spec.name))
                        try:
                            with _tracing.execution_span(spec.name, trace_ctx):
                                return method(*args, **kwargs)
                        finally:
                            # flush BEFORE clearing: a partial line left
                            # in the tee's thread buffer would otherwise
                            # prepend itself to the NEXT task's output
                            try:
                                sys.stdout.flush()
                                sys.stderr.flush()
                            except (OSError, ValueError):
                                pass  # stream closed mid-teardown
                            log_ctx_var.reset(_log_tok)

                    # sync methods of a named group run on that group's
                    # dedicated pool: a flooded default lane can never
                    # hold a group lane's threads
                    _pool = getattr(self, "_group_pools", {}).get(
                        spec.kwargs.get("__rt_group__"), self._exec_pool
                    )
                    value = await loop.run_in_executor(_pool, _call_method)
            else:

                def _call():
                    from ray_tpu.core.log_stream import log_ctx_var

                    self._task_local.task_id = spec.task_id
                    # overwrite-by-design: see the async path above
                    _ambient_deadline.set(spec.deadline_s)  # rtlint: disable=RT006
                    _log_tok = log_ctx_var.set((spec.owner, spec.name))
                    # registered for mid-execution cancellation
                    # (_h_cancel_task async-raises into this thread);
                    # register/pop under _state_lock so a cancel can
                    # never target a recycled pool thread running a
                    # different task
                    with self._state_lock:
                        self._task_threads[tid] = threading.get_ident()
                    committed = False
                    value = None
                    try:
                        try:
                            with _tracing.execution_span(spec.name, trace_ctx):
                                value = fn(*args, **kwargs)
                                committed = True
                            return value
                        finally:
                            # partial printed lines ship before the
                            # context clears
                            try:
                                sys.stdout.flush()
                                sys.stderr.flush()
                            except (OSError, ValueError):
                                pass  # stream closed mid-teardown
                            log_ctx_var.reset(_log_tok)
                            # after this pop no NEW cancel can be
                            # delivered (raise and pop share the lock)
                            with self._state_lock:
                                self._task_threads.pop(tid, None)
                    except exc.TaskCancelledError:
                        # async-raised cancels land at an arbitrary later
                        # bytecode boundary: one delivered anywhere after
                        # fn() completed (span exit, the pop above) must
                        # not turn the finished task into a cancellation.
                        # A residual window remains between fn returning
                        # and `committed = True` — the raise cannot be
                        # made atomic with the call's last bytecode.
                        with self._state_lock:
                            # the cancel may have aborted the finally
                            # BETWEEN lock acquire and pop: re-pop so no
                            # stale tid->ident mapping survives
                            self._task_threads.pop(tid, None)
                        if committed:
                            return value
                        raise

                value = await loop.run_in_executor(self._exec_pool, _call)
            # the function has returned: drop the executor's own
            # references to the (possibly shm-pinned) args BEFORE
            # packaging the returns.  Packaging may have to wait for
            # store space, and an input pin held across that wait is
            # space the spiller can never free — with several producers
            # packaging at once, inputs-pinned-against-outputs
            # deadlocked the store under storage-fault rework storms.
            # (Args whose values the RESULT still references stay alive
            # through the result, exactly as they should.)
            del args, kwargs
            if spec.is_streaming:
                try:
                    n_items = await self._stream_out(spec, value, conn)
                finally:
                    # cancel marks are per-execution: never leak into a
                    # retry of the same task id
                    getattr(self, "_cancelled_streams", set()).discard(tid)
                result = TaskResult(
                    task_id=spec.task_id,
                    status="ok",
                    returns=[],
                    execution_info={"duration": time.time() - t0,
                                    "num_items": n_items},
                )
            else:
                returns = await self._package_returns(spec, value)
                result = TaskResult(
                    task_id=spec.task_id,
                    status="ok",
                    returns=returns,
                    execution_info={"duration": time.time() - t0},
                )
        except Exception as e:  # noqa: BLE001 - user exception boundary
            tb = traceback.format_exc()
            if isinstance(e, exc.TaskCancelledError):
                # preserve the type: callers match on TaskCancelledError
                # (the async-raised mid-execution interrupt lands here)
                err: Exception = exc.TaskCancelledError(task_id=spec.task_id)
            else:
                err = exc.TaskError(
                    str(e), remote_traceback=tb, cause_type=type(e).__name__
                )
            envelope = ser.serialize_to_bytes(err, tag=ser.TAG_ERROR)
            result = TaskResult(task_id=spec.task_id, status="error", error=envelope)
        self._started_tasks.discard(tid)
        # any borrows this task registered while deserializing its args
        # must be ACKed by their owners before the result releases the
        # caller's transit pins (the forwarded-ref ordering guarantee)
        await self._await_borrow_acks()
        # coalesced reply: results for this owner produced within the
        # same loop tick ship as ONE task_result_batch frame (the
        # coalescer handles the origin-gone fallback via the daemon)
        self._result_coalescer.enqueue(conn, spec.owner, result)

    async def _await_borrow_acks(self, timeout: float = 10.0):
        # SNAPSHOT, don't drain: with concurrent tasks in one worker
        # (async actors, max_concurrency>1) a swap would let task A
        # steal task B's outstanding ack, so B's result could outrun
        # B's borrow registration.  Completed futures are pruned after.
        with self._state_lock:
            acks = list(self._pending_borrow_acks)
        for f in acks:
            try:
                await asyncio.wait_for(asyncio.wrap_future(f), timeout)
            except Exception as e:
                # owner unreachable: proceed — the caller-side pin falls
                # back to the (pre-existing) unprotected window
                logger.debug("borrow ACK not confirmed: %s", e)
        with self._state_lock:
            self._pending_borrow_acks = [
                f for f in self._pending_borrow_acks if not f.done()
            ]

    async def _stream_out(self, spec: TaskSpec, value, conn) -> int:
        """Drive a streaming-generator task's iteration: each yielded
        item is packaged like a return value and pushed to the owner as
        a `stream_item` ahead of the final task_result (reference:
        streaming generators, `task_manager.h:208`).  A non-generator
        return value becomes a single-item stream."""
        import inspect

        from ray_tpu.util import tracing as _tracing

        loop = asyncio.get_running_loop()
        _END = object()
        index = 0
        tid = spec.task_id.binary()
        # the execution_span that wrapped generator CREATION has already
        # exited by the time the body runs here — re-install the task's
        # trace context around iteration so spans opened inside the
        # generator (engine ticks, nested submits) join the request's
        # trace instead of fragmenting.  A stream span wraps the whole
        # drive; its context is what generator frames see.
        trace_ctx = getattr(spec, "trace_ctx", None)
        stream_span = None
        stream_ctx = None
        if trace_ctx is not None:
            with _tracing.use_context(trace_ctx):
                stream_span = _tracing.start_span(f"stream:{spec.name}",
                                                  kind="CONSUMER")
            stream_ctx = _tracing.ctx_of(stream_span)

        def _abandoned() -> bool:
            cancelled = getattr(self, "_cancelled_streams", None)
            if cancelled and tid in cancelled:
                cancelled.discard(tid)
                return True
            return False

        async def _send(item):
            nonlocal index
            index += 1
            oid = ObjectID.for_return(spec.task_id, index)
            ret = await self._package_value(oid, item)
            payload = {"task_id": spec.task_id, "index": index, "item": ret,
                       "owner": spec.owner}
            try:
                conn.send("stream_item", payload)
            except Exception as e:
                # origin conn gone: route via the node daemon
                logger.debug("direct stream_item failed (%s); routing "
                             "via noded", e)
                self.noded.send("task_stream", payload)

        try:
            if inspect.isasyncgen(value):
                with _tracing.use_context(stream_ctx):
                    async for item in value:
                        await _send(item)
                        if _abandoned():
                            # user generator's finally runs
                            await value.aclose()
                            break
            elif inspect.isgenerator(value):

                def _next():
                    # run_in_executor does not propagate contextvars:
                    # re-install the stream context on the pool thread
                    # so the generator body's spans/submits stay in the
                    # request's trace
                    with _tracing.use_context(stream_ctx):
                        try:
                            return next(value)
                        except StopIteration:
                            return _END

                # a grouped streaming method iterates on its group's pool
                # (same isolation rule as _exec_task's sync-method path)
                _pool = getattr(self, "_group_pools", {}).get(
                    spec.kwargs.get("__rt_group__"), self._exec_pool
                )
                while True:
                    item = await loop.run_in_executor(_pool, _next)
                    if item is _END:
                        break
                    await _send(item)
                    if _abandoned():
                        await loop.run_in_executor(_pool, value.close)
                        break
            else:
                await _send(value)
        except BaseException as e:
            _tracing.finish_span(stream_span, error=type(e).__name__)
            raise
        _tracing.finish_span(stream_span)
        return index

    async def _create_with_backpressure(self, id_bytes: bytes, total: int,
                                        timeout_s: float = 60.0):
        """Blocking-create semantics (reference: plasma's
        create_request_queue.h — creates wait under memory pressure
        instead of failing): on a full store, ask the node daemon to
        spill urgently and retry until the deadline.

        Returns None when a SEALED copy already exists: a prior attempt
        of this task (a retry after a mid-packaging failure, or a
        lineage resubmit racing a concurrent restore) already produced
        this return — task bodies on this plane are deterministic, so
        the existing bytes ARE this attempt's value and the caller
        skips the write.  An UNSEALED collision is a dead attempt's
        partial write: delete it and recreate."""
        from ray_tpu.shm import ObjectExistsError, StoreFullError

        deadline = time.time() + timeout_s
        attempts = 0
        disk_full_streak = 0
        while True:
            try:
                # no destructive eviction: pressure resolves by spilling
                # (primaries survive on disk) rather than data loss
                return self.store.create(id_bytes, total, allow_evict=False)
            except ObjectExistsError:
                if self.store.contains(id_bytes):  # sealed: reuse
                    return None
                self.store.delete(id_bytes)
                if time.time() > deadline:
                    raise
                # the collision may be an unsealed entry pinned by a
                # live writer (e.g. a concurrent restore): yield the
                # loop instead of spinning hot until it seals or dies
                await asyncio.sleep(0.05)  # rtlint: disable=RT006 - local store-state poll, not a networked retry storm
            except StoreFullError:
                if time.time() > deadline:
                    raise
                reply = None
                try:
                    # escalate: watermark-target spills first; if the
                    # create is still blocked after a few passes (free
                    # bytes too fragmented for a contiguous region),
                    # drain every unpinned object
                    reply = await self.noded.call(
                        "spill_now", {"drain": attempts >= 2}, timeout=10
                    )
                except Exception as e:
                    logger.debug("spill_now nudge failed: %s", e)
                disk_full_streak = _spill_clamp_streak(
                    reply, disk_full_streak
                )
                attempts += 1
                await asyncio.sleep(0.05)

    async def _package_returns(self, spec: TaskSpec, value) -> List[Tuple]:
        import inspect as _inspect

        if _inspect.isgenerator(value) or _inspect.isasyncgen(value):
            raise TypeError(
                f"task {spec.name!r} returned a generator but was not "
                "submitted as streaming — call it with "
                "num_returns=\"streaming\" (generator functions and "
                "public generator actor methods stream automatically)"
            )
        if spec.num_returns == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task declared num_returns={spec.num_returns} but returned "
                    f"{len(values)} values"
                )
        out = []
        for i, v in enumerate(values):
            oid = ObjectID.for_return(spec.task_id, i + 1)
            out.append(await self._package_value(oid, v))
        return out

    async def _package_value(self, oid: ObjectID, v) -> Tuple:
        """Serialize one return value: inline bytes when small, sealed
        into the local shm store when large.  Refs captured inside the
        value ride along as `(id, owner)` pairs so the receiving owner
        can register borrows keyed to the container — that converts this
        executor's transient contained-pin and lets the pins release
        when the container is freed instead of at job exit (closing the
        leak the round-1 design documented; reference:
        `reference_count.h:64` contained-refs edges).  Foreign-owned
        refs forwarded in the value additionally get transit pins (see
        `_pin_transit`) keyed to the task, released when the result's
        owner confirms it registered the contained borrows
        (`transit_release`)."""
        chunks, total, captured = ser.serialize(v)
        self._pin_contained(captured)
        ret_transit: list = []
        self._pin_transit(captured, ret_transit)
        if ret_transit:
            tid = oid.task_id().binary()
            self._return_transit.setdefault(tid, []).extend(ret_transit)
        contained = [
            (r.binary(), tuple(r.owner))
            for r in captured
            if r.owner is not None
        ]
        if total <= self.cfg.max_direct_call_object_size:
            buf = bytearray(total)
            ser.write_chunks(chunks, memoryview(buf))
            return (_INLINE, bytes(buf), contained)
        dest = await self._create_with_backpressure(oid.binary(), total)
        if dest is not None:  # None: a prior attempt's sealed copy stands
            ser.write_chunks(chunks, dest)
            del dest
            self.store.seal(oid.binary())
        return (_SHM, self.node_id, total, contained)

    async def _load_function(self, spec: TaskSpec):
        if spec.actor_id is not None:
            return None
        fn = self._fn_cache.get(spec.function_id)
        if fn is None:
            blob = spec.function_blob
            if blob is None:
                blob = await self.controller.call(
                    "kv_get", {"key": "fn:" + spec.function_id.hex()}
                )
                if blob is None:
                    raise exc.RayTpuError(
                        f"function {spec.function_id.hex()} not found"
                    )
            try:
                fn = ser.loads(blob)
            except ModuleNotFoundError:
                if not await self._adopt_driver_sys_path():
                    raise
                fn = ser.loads(blob)
            self._fn_cache[spec.function_id] = fn
        return fn


class ObjectRefGenerator:
    """Iterator over the ObjectRefs of a streaming-generator task
    (`num_returns="streaming"`).  Reference: `ObjectRefGenerator` in
    `_raylet.pyx` — each `next()` blocks until the executor yields the
    next item and returns that item's ObjectRef; a mid-stream exception
    in the generator body raises at the position it occurred.
    """

    def __init__(self, task_id_bytes: bytes, runtime: "Runtime"):
        self._tid = task_id_bytes
        self._rt = runtime

    @property
    def task_id(self) -> bytes:
        return self._tid

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        ref = self._rt.stream_next(self._tid)
        if ref is None:
            raise StopIteration
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        loop = asyncio.get_running_loop()
        if loop is self._rt.loop:
            # on the runtime's io loop (async actors, serve proxy):
            # await natively — no thread blocked per waiting stream
            ref = await self._rt._stream_next_async(self._tid)
        else:
            ref = await loop.run_in_executor(
                None, self._rt.stream_next, self._tid
            )
        if ref is None:
            raise StopAsyncIteration
        return ref

    def __del__(self):
        # abandoned before exhaustion: drop the owner-side stream state
        # (exhausted streams already popped it — this is a no-op then)
        try:
            self._rt.stream_release(self._tid)
        except Exception:  # rtlint: disable=RT005
            # __del__ during interpreter teardown: logging itself may
            # already be torn down
            pass

    def __repr__(self):
        return f"ObjectRefGenerator({self._tid.hex()})"


# ----------------------------------------------------------------------
# module-level runtime + hooks used by ObjectRef
# ----------------------------------------------------------------------
_runtime: Optional[Runtime] = None


def _strategy_from_options(options):
    from ray_tpu.util.scheduling_strategies import pg_id_bytes, to_internal

    s = options.get("scheduling_strategy")
    if s is None:
        pg = options.get("placement_group")
        if pg is not None:
            return SchedulingStrategy(
                kind="placement_group",
                pg_id=pg_id_bytes(pg),
                pg_bundle_index=options.get("placement_group_bundle_index", -1),
            )
        return SchedulingStrategy()
    return to_internal(s)


def get_runtime() -> Runtime:
    if _runtime is None:
        raise exc.RayTpuError(
            "ray_tpu is not initialized; call ray_tpu.init() first"
        )
    return _runtime


def set_runtime(rt: Optional[Runtime]):
    global _runtime
    _runtime = rt


def is_initialized() -> bool:
    return _runtime is not None


def on_ref_deserialized(ref: ObjectRef):
    rt = _runtime
    if rt is None or rt._shutdown:
        return
    with rt._state_lock:
        rc = rt.refs.setdefault(ref.binary(), _RefCount())
        rc.local += 1
        if _RECORD_CALLSITES and not rc.callsite:
            rc.callsite = _creation_site()
        if ref.owner is not None and tuple(ref.owner) == rt.address:
            rc.contained = 0  # owner consumed its own container: pin -> local
        # `registered` (not a local==1 heuristic) drives exactly one
        # add/remove pair per entry lifetime: transit pins can hold the
        # entry across local 1->0->1 cycles, where re-counting would
        # double-register at the owner
        is_new_borrow = (
            not rc.registered
            and ref.binary() not in rt.objects
            and ref.owner is not None
            and tuple(ref.owner) != rt.address
        )
        if is_new_borrow:
            rc.registered = True
            rc.owner_addr = tuple(ref.owner)
    if is_new_borrow and rt.noded is not None:
        payload = {
            "target": tuple(ref.owner),
            "method": "add_borrow",
            "payload": {"id": ref.binary(), "borrower": rt.address},
        }
        if rt.mode == "worker":
            # workers forward refs onward in their RESULTS: the owner
            # must have this registration on the books before our task
            # result lets the caller drop ITS protection, so ride a
            # want_reply call whose ack the executor awaits before
            # sending any task result
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    rt.noded.call("route", {**payload, "want_reply": True}),
                    rt.loop,
                )
                with rt._state_lock:
                    # under the lock: _await_borrow_acks rebuilds this
                    # list during its prune, and a bare append could be
                    # lost to that assignment
                    rt._pending_borrow_acks.append(fut)
            except Exception as e:
                logger.debug("borrow ACK registration failed: %s", e)
        else:
            # drivers don't forward refs in results: the registration
            # needs no ACK, so it rides the coalesced channel (a 10k-ref
            # get registers in ~10 frames, not 10k)
            rt._queue_ref_event(
                tuple(ref.owner), "add_borrow",
                {"id": ref.binary(), "borrower": rt.address},
            )


def on_ref_deleted(ref: ObjectRef):
    rt = _runtime
    if rt is None or rt._shutdown:
        return
    with rt._state_lock:
        rc = rt.refs.get(ref.binary())
        if rc is None:
            return
        rc.local -= 1
        if rc.owner_addr is None and ref.owner is not None:
            rc.owner_addr = tuple(ref.owner)
        # _maybe_free sends the final remove_borrow when the entry dies
        rt._maybe_free(ref.binary())


async def async_get(ref: ObjectRef):
    return await get_runtime()._get_one(ref)


def as_future(ref: ObjectRef):
    rt = get_runtime()
    return asyncio.run_coroutine_threadsafe(rt._get_one(ref), rt.loop)


def _unwrap(tag: int, value):
    if tag == ser.TAG_ERROR:
        raise value
    return value


def _spill_clamp_streak(reply, streak: int) -> int:
    """Shared disk-full admission clamp for the blocked-create loops
    (driver put and worker return packaging).  Counts CONSECUTIVE
    spill_now replies that reported a full spill disk with nothing
    spilled — one such reply can be a transient ENOSPC burst — and at
    three in a row raises typed `BackPressureError` (the PR 10/11
    admission-clamp convention): the store is full AND the disk keeps
    refusing bytes, so no amount of waiting unblocks the create."""
    if reply and reply.get("disk_full") and not reply.get("spilled"):
        streak += 1
    else:
        streak = 0
    if streak >= 3:
        raise exc.BackPressureError(
            "object store is full and the spill disk is out of "
            "space; shed load or free disk",
            retry_after_s=5.0,
        )
    return streak


def _error_from_envelope(envelope: bytes) -> BaseException:
    tag, err = ser.deserialize(memoryview(envelope))
    if isinstance(err, BaseException):
        return err
    return exc.RayTpuError(str(err))
