"""Disk I/O chokepoint + the injectable disk-fault model.

Every byte the object plane persists or reads back from disk — spill
files, spill manifests, restore reads, controller snapshots — passes
through :func:`write_file` / :func:`read_file` here.  That single seam
is what makes storage failure a *testable* domain: `DiskChaos` is the
disk-side sibling of `rpc.NetworkChaos` (`core/rpc.py:60`) and injects
the four storage faults that matter, deterministically, from a seed:

- **ENOSPC**: the write raises ``OSError(errno.ENOSPC)`` before any
  byte lands (a full disk refuses the allocation).
- **EIO**: a read or write raises ``OSError(errno.EIO)`` (a dying
  device; often transient — callers retry through `core/retry.py`).
- **torn write**: a *prefix* of the data is persisted, then the write
  fails with EIO — the crash-mid-write shape that leaves a short file
  behind when the caller skips the atomic tmp+rename dance.
- **bit flip**: one bit of the persisted (or read-back) payload flips
  *silently* — the fault class only end-to-end checksums can catch.

Faults match by path substring (``match``), draw from one seeded RNG,
and can be bounded (``max_faults``) to model transient errors.  Enable
per process via :func:`set_disk_chaos`, or for spawned daemons/workers
via ``RT_DISK_CHAOS`` (JSON kwargs) in their environment — mirroring
``RT_CHAOS`` exactly.

The real I/O path stays boring: atomic writes are tmp + ``os.replace``
with the tmp unlinked on any failure, so a failed write never leaves a
half-file where a reader will trust it.
"""

from __future__ import annotations

import errno
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class DiskChaos:
    """Seeded, deterministic disk-fault model applied at the
    `diskio` chokepoint.

    Probabilities are per-operation; ``match`` restricts faults to
    paths containing the substring (e.g. ``"spilled"`` hits only the
    spill directory, leaving session logs alone).  ``max_faults``
    bounds the TOTAL number of injected faults (0 = unlimited) —
    ``max_faults=2`` with ``eio_prob=1.0`` models a device that fails
    twice then recovers, which is what retry-path tests want.
    ``free_bytes`` (when not None) overrides what
    :func:`free_bytes` reports, so low-disk watermark behavior is
    testable without actually filling a disk.
    """

    def __init__(self, enospc_prob: float = 0.0, eio_prob: float = 0.0,
                 torn_write_prob: float = 0.0, bit_flip_prob: float = 0.0,
                 eio_read_prob: Optional[float] = None,
                 eio_write_prob: Optional[float] = None,
                 match: str = "", seed: int = 0, max_faults: int = 0,
                 free_bytes: Optional[int] = None):
        import random

        self.enospc_prob = float(enospc_prob)
        self.eio_prob = float(eio_prob)
        # per-direction EIO overrides (default: the shared eio_prob) —
        # a restore-retry test wants a device that fails READS only
        self.eio_read_prob = float(
            eio_prob if eio_read_prob is None else eio_read_prob
        )
        self.eio_write_prob = float(
            eio_prob if eio_write_prob is None else eio_write_prob
        )
        self.torn_write_prob = float(torn_write_prob)
        self.bit_flip_prob = float(bit_flip_prob)
        self.match = match
        self.seed = int(seed)
        self.max_faults = int(max_faults)
        self.free_bytes = free_bytes
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # injected-fault ledger: kind -> count (tests and the perf
        # harness read this to prove the schedule actually fired)
        self.faults: Dict[str, int] = {}

    def _charge(self, kind: str) -> bool:
        """Record one fault of `kind`; False when the budget is spent."""
        total = sum(self.faults.values())
        if self.max_faults and total >= self.max_faults:
            return False
        self.faults[kind] = self.faults.get(kind, 0) + 1
        return True

    def plan_write(self, path: str, size: int):
        """-> (fault_kind or None, torn_prefix_len, flip_bit_index)
        for one write of `size` bytes to `path`."""
        with self._lock:
            if self.match and self.match not in path:
                return None, 0, 0
            r = self._rng
            if self.enospc_prob and r.random() < self.enospc_prob:
                if self._charge("enospc"):
                    return "enospc", 0, 0
            if self.torn_write_prob and r.random() < self.torn_write_prob:
                if self._charge("torn_write"):
                    return "torn_write", r.randrange(max(1, size)), 0
            if self.eio_write_prob and r.random() < self.eio_write_prob:
                if self._charge("eio_write"):
                    return "eio", 0, 0
            if (self.bit_flip_prob and size > 0
                    and r.random() < self.bit_flip_prob):
                if self._charge("bit_flip_write"):
                    return "bit_flip", 0, r.randrange(size * 8)
            return None, 0, 0

    def plan_read(self, path: str, size: int):
        """-> (fault_kind or None, flip_bit_index) for one read."""
        with self._lock:
            if self.match and self.match not in path:
                return None, 0
            r = self._rng
            if self.eio_read_prob and r.random() < self.eio_read_prob:
                if self._charge("eio_read"):
                    return "eio", 0
            if (self.bit_flip_prob and size > 0
                    and r.random() < self.bit_flip_prob):
                if self._charge("bit_flip_read"):
                    return "bit_flip", r.randrange(size * 8)
            return None, 0

    def plan_free_bytes(self) -> Optional[int]:
        return self.free_bytes

    def __repr__(self):
        knobs = {k: v for k, v in (
            ("enospc", self.enospc_prob), ("eio", self.eio_prob),
            ("torn", self.torn_write_prob), ("flip", self.bit_flip_prob),
        ) if v}
        return (f"DiskChaos(seed={self.seed}, match={self.match!r}, "
                f"{knobs}, injected={dict(self.faults)})")


_chaos: Optional[DiskChaos] = None
_chaos_env_checked = False


def set_disk_chaos(chaos: Optional[DiskChaos]) -> None:
    """Install (or clear, with None) this process's disk-fault model."""
    global _chaos, _chaos_env_checked
    _chaos = chaos
    _chaos_env_checked = True


def get_disk_chaos() -> Optional[DiskChaos]:
    """Active disk-fault model; lazily constructed from RT_DISK_CHAOS
    for child processes (daemons/workers inherit the env)."""
    global _chaos, _chaos_env_checked
    if not _chaos_env_checked:
        _chaos_env_checked = True
        import json as _json

        raw = os.environ.get("RT_DISK_CHAOS")
        if raw:
            try:
                _chaos = DiskChaos(**_json.loads(raw))
            except Exception:
                logger.warning("bad RT_DISK_CHAOS %r ignored", raw)
    return _chaos


def _flip_bit(data: bytes, bit_index: int) -> bytes:
    buf = bytearray(data)
    buf[bit_index // 8] ^= 1 << (bit_index % 8)
    return bytes(buf)


def write_file(path: str, data, atomic: bool = True) -> None:
    """Persist `data` at `path` through the fault seam.

    atomic=True (the default, and what every spill/manifest/snapshot
    writer uses) stages to ``path + ".tmp"`` and ``os.replace``s, so a
    failed write never leaves a half-file under the final name; the
    tmp is unlinked on ANY failure.  Raises OSError on fault — real
    (the disk's) or injected (DiskChaos's); callers cannot tell the
    difference, which is the point.
    """
    data = bytes(data)
    chaos = get_disk_chaos()
    fault, torn_len, flip_bit = (None, 0, 0)
    if chaos is not None:
        fault, torn_len, flip_bit = chaos.plan_write(path, len(data))
    if fault == "enospc":
        raise OSError(errno.ENOSPC, "no space left on device (injected)",
                      path)
    if fault == "bit_flip":
        data = _flip_bit(data, flip_bit)
    target = path + ".tmp" if atomic else path
    try:
        with open(target, "wb") as f:
            if fault == "torn_write":
                f.write(data[:torn_len])
                f.flush()
                raise OSError(errno.EIO,
                              "I/O error mid-write (injected torn write)",
                              path)
            f.write(data)
            if fault == "eio":
                raise OSError(errno.EIO, "I/O error (injected)", path)
        if atomic:
            os.replace(target, path)
    except BaseException:
        if atomic:
            try:
                os.unlink(target)
            except OSError:
                pass
        else:
            # non-atomic writers asked for in-place semantics; a torn
            # short file IS the observable failure mode they model
            pass
        raise


def read_file(path: str) -> bytes:
    """Read `path` fully through the fault seam.  Raises OSError on
    real or injected faults; a bit-flip fault returns silently
    corrupted bytes — detecting that is the checksum layer's job."""
    chaos = get_disk_chaos()
    if chaos is not None:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = 0
        fault, flip_bit = chaos.plan_read(path, size)
        if fault == "eio":
            raise OSError(errno.EIO, "I/O error (injected)", path)
    else:
        fault, flip_bit = None, 0
    with open(path, "rb") as f:
        data = f.read()
    if fault == "bit_flip" and data:
        data = _flip_bit(data, flip_bit % (len(data) * 8))
    return data


def free_bytes(path: str) -> int:
    """Free bytes on the filesystem holding `path` (the low-disk
    watermark input).  DiskChaos's `free_bytes` override wins, so
    disk-full *election* behavior is testable on a roomy disk."""
    chaos = get_disk_chaos()
    if chaos is not None:
        override = chaos.plan_free_bytes()
        if override is not None:
            return int(override)
    try:
        st = os.statvfs(path)
    except OSError:
        # a path that doesn't exist yet: judge its parent; total
        # failure degrades to "plenty" (the write itself still fails
        # loudly if the disk really is full)
        try:
            st = os.statvfs(os.path.dirname(path) or ".")
        except OSError:
            return 1 << 62
    return st.f_bavail * st.f_frsize
