"""Owner shard: one submission/completion lane of the driver's owner
plane.

The task hot path used to live entirely on the runtime's single io
loop (~580 us of driver CPU per task on one core — PERF.md's measured
cost model), which caps one driver at ~1.7k tasks/s no matter how many
cores the head node has.  `Runtime` now owns N of these shards, keyed
by task id: each shard runs its own asyncio loop on its own thread,
holds its own connection to the node daemon, negotiates its own worker
leases (batched: one `request_lease` round carries `count` grants for a
submission burst), and receives its own completion frames (coalesced:
executors reply `task_result_batch` per connection tick).  Shard state
— lease pools, in-flight assignment — is guarded by a shard-local lock;
cross-shard object/ref state stays in the runtime under `_state_lock`
(lock order: `_state_lock` outer, `shard.lock` inner, never reversed).

With `owner_shards = 1` (the default) the shard shares the runtime's io
loop and node connection — byte-for-byte the classic single-owner
plane.

Reference analog: the GCS/raylet split of SURVEY layers 3-4, which is
what lets the reference drain 1M queued tasks across 64 cores; here the
split is owner-internal because the owner (not the daemon) is the
measured bottleneck (~580 us vs ~30 us per task).
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time
from collections import deque
from typing import Dict, Optional, Tuple

from ray_tpu.core import rpc
from ray_tpu.core.retry import backoff_delay_s
from ray_tpu.core.task_spec import TaskResult, TaskSpec
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.util import sanitizer as _sanitizer

logger = logging.getLogger(__name__)

# Max tasks pushed ahead of completion on one leased worker (the
# reference's max_tasks_in_flight_per_worker).  The worker runs normal
# tasks on a thread pool at least this wide, so a task that blocks
# (collectives, nested gets) never deadlocks a pipelined successor and
# short tasks are not serialized behind long ones.
PIPELINE_DEPTH = 4


class Lease:
    """One leased worker with pipelined pushes."""

    __slots__ = ("worker_id", "conn", "in_flight", "assigned", "idle_token",
                 "socket_path")

    def __init__(self, worker_id: str, conn: rpc.Connection,
                 socket_path: str = ""):
        self.worker_id = worker_id
        self.conn = conn
        self.in_flight = 0
        self.assigned: Dict[bytes, TaskSpec] = {}
        # bumped each time the lease goes idle; lets the delayed-return
        # timer detect an intervening busy period and stand down
        self.idle_token = 0
        # breaker-board key material: the breaker for a retired socket
        # is dropped on close so the board stays bounded by live peers
        self.socket_path = socket_path


class LeasePool:
    """Per-resource-signature pool of leased workers + overflow queue
    (reference: one lease request pipeline per SchedulingKey,
    `normal_task_submitter.h`)."""

    __slots__ = ("sig", "demand", "leases", "queue", "requesting",
                 "env_hash", "container")

    def __init__(self, sig, demand):
        self.sig = sig
        self.demand = demand
        self.leases: Dict[str, Lease] = {}
        self.queue: deque = deque()
        self.container = None
        self.requesting = False
        self.env_hash: Optional[str] = None  # runtime-env dedication


def _thread_cpu_seconds(native_tid: Optional[int]) -> float:
    """CPU seconds burned by one kernel thread of this process, from
    /proc (utime+stime) — readable from ANY thread, unlike
    CLOCK_THREAD_CPUTIME_ID.  Feeds the per-shard us/task accounting
    perf.py reports."""
    if native_tid is None:
        return 0.0
    try:
        with open(f"/proc/self/task/{native_tid}/stat") as f:
            stat = f.read()
    except OSError:
        return 0.0
    rest = stat.rsplit(")", 1)[1].split()
    return (int(rest[11]) + int(rest[12])) / os.sysconf("SC_CLK_TCK")


class OwnerShard:
    """One lane of the owner plane: submission loop, lease pools, and
    completion ingestion for the tasks whose ids hash here."""

    def __init__(self, rt, index: int, shared: bool):
        self.rt = rt
        self.index = index
        # shared=True: ride the runtime's io loop + noded conn (the
        # classic single-owner plane; owner_shards == 1)
        self.shared = shared
        self.loop: asyncio.AbstractEventLoop = (
            rt.loop if shared else asyncio.new_event_loop()
        )
        if not shared:
            _sanitizer.register_loop(
                self.loop, f"rt-owner-{index}", audit_timers=False
            )
        self.noded: Optional[rpc.Connection] = None
        self.thread: Optional[threading.Thread] = None
        self.native_tid: Optional[int] = None
        # guards pools/conn_lease/counters; NEVER held across an await
        # and NEVER taken before acquiring rt._state_lock (lock order:
        # _state_lock outer, shard.lock inner)
        self.lock = _sanitizer.wrap_lock(
            threading.Lock(), f"shard[{index}].lock", _sanitizer.SHARD_LOCK
        )
        self.pools: Dict[tuple, LeasePool] = {}
        self.conn_lease: Dict[rpc.Connection, Tuple[LeasePool, Lease]] = {}
        self.lease_timers: set = set()
        # live _acquire_leases tasks, cancelled at close so loop stop
        # never destroys one mid-await
        self._acquire_tasks: set = set()
        # per-shard accounting (normal tasks only): submitted bumps at
        # submit_task registration, completed at the exactly-once
        # pending_tasks pop in _complete_task — their sum across shards
        # must equal the single-owner totals (tests/test_owner_shards.py)
        self.submitted = 0
        self.completed = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self, node_socket: str):
        if self.shared:
            self.noded = self.rt.noded
            self.native_tid = getattr(self.rt, "_io_native_tid", None)
            return
        self.thread = threading.Thread(
            target=self._run_loop, name=f"rt-owner-{self.index}", daemon=True
        )
        self.thread.start()
        fut = asyncio.run_coroutine_threadsafe(
            self._connect(node_socket), self.loop
        )
        fut.result(timeout=self.rt.cfg.rpc_connect_timeout_s)

    def _run_loop(self):
        asyncio.set_event_loop(self.loop)
        self.native_tid = threading.get_native_id()
        self.loop.run_forever()

    async def _connect(self, node_socket: str):
        # unregistered with the daemon (holder shows as "remote"): the
        # runtime's MAIN connection carries the owner identity; routed
        # frames still land there, only lease traffic rides this one
        self.noded = await rpc.connect_unix(
            node_socket, handler=self.rt._handle,
            name=f"noded-s{self.index}",
        )

    def stop(self):
        """Close this shard's connections and (own-loop shards) stop the
        loop.  Called from the runtime's shutdown path, any thread."""
        async def _close():
            await self.close_shared()
            if self.noded is not None:
                await self.noded.close()

        if self.shared:
            # the runtime's own shutdown coroutine runs _close on the
            # shared loop; nothing to stop here
            return
        try:
            asyncio.run_coroutine_threadsafe(_close(), self.loop).result(
                timeout=5
            )
        except Exception as e:
            logger.debug("shard %d close incomplete: %s", self.index, e)
        self.loop.call_soon_threadsafe(self.loop.stop)
        if self.thread is not None:
            self.thread.join(timeout=5)

    async def close_shared(self):
        """Close this shard's lease-plane state: timers, acquire loops,
        worker conns.  Awaited inside the runtime's shutdown coroutine
        for shared-loop shards; `stop()` wraps it (plus the noded-conn
        close) for own-loop shards."""
        for timer in list(self.lease_timers):
            timer.cancel()
        self.lease_timers.clear()
        for task in list(self._acquire_tasks):
            task.cancel()
        self._acquire_tasks.clear()
        for conn in list(self.conn_lease):
            await conn.close()

    def cpu_seconds(self) -> float:
        return _thread_cpu_seconds(self.native_tid)

    def stats(self) -> Dict[str, float]:
        with self.lock:
            submitted, completed = self.submitted, self.completed
            n_leases = sum(len(p.leases) for p in self.pools.values())
            queued = sum(len(p.queue) for p in self.pools.values())
        return {
            "shard": self.index,
            "submitted": submitted,
            "completed": completed,
            "leases": n_leases,
            "queued": queued,
            "cpu_s": round(self.cpu_seconds(), 3),
        }

    # ------------------------------------------------------------------
    # submission (calling thread — must not block on the shard loop)
    # ------------------------------------------------------------------
    def pool_for(self, spec: TaskSpec) -> LeasePool:
        demand = spec.resources.as_dict()
        sig = (tuple(sorted(demand.items())), spec.env_hash)
        with self.lock:
            pool = self.pools.get(sig)
        if pool is None:
            pool = LeasePool(sig, demand)
            pool.env_hash = spec.env_hash
            # container envs ride the lease request so the daemon can
            # spawn the worker INSIDE the image (core/container.py)
            from ray_tpu.core.container import container_section

            pool.container = container_section(
                getattr(spec, "runtime_env", None)
            )
            with self.lock:
                pool = self.pools.setdefault(sig, pool)
        return pool

    def push(self, spec: TaskSpec):
        """Push a default-strategy task onto the least-loaded lease with
        pipeline room, else queue it and (once) start the lease
        acquisition loop on this shard's event loop."""
        pool = self.pool_for(spec)
        need_request = False
        with self.lock:
            lease = None
            for cand in pool.leases.values():
                if cand.in_flight < PIPELINE_DEPTH and (
                    lease is None or cand.in_flight < lease.in_flight
                ):
                    lease = cand
            if lease is not None:
                lease.in_flight += 1
                lease.assigned[spec.task_id.binary()] = spec
            else:
                pool.queue.append(spec)
                need_request = not pool.requesting
                if need_request:
                    pool.requesting = True
        if lease is not None:
            try:
                lease.conn.send_threadsafe("execute_task", spec)
            except rpc.ConnectionLost:
                pass  # teardown requeues/fails via on_lease_conn_closed
        elif need_request:
            self.loop.call_soon_threadsafe(self._spawn_acquire, pool)

    def _spawn_acquire(self, pool: LeasePool):
        task = asyncio.ensure_future(self._acquire_leases(pool))
        self._acquire_tasks.add(task)
        task.add_done_callback(self._acquire_tasks.discard)

    # ------------------------------------------------------------------
    # lease acquisition (shard loop) — batched negotiation
    # ------------------------------------------------------------------
    async def _acquire_leases(self, pool: LeasePool):
        """Request leases from the node daemon while demand persists
        (reference: RequestNewWorkerIfNeeded,
        `normal_task_submitter.cc:299`).  Batch-first: one
        `request_lease` round asks for up to `lease_request_batch`
        grants sized to the queue, amortizing the RPC + daemon pass
        over a whole submission burst."""
        rt = self.rt
        rpc_failures = 0
        dry_rounds = 0
        try:
            while not rt._shutdown:
                with self.lock:
                    # prefer one lease per queued task; deep pipelines
                    # only absorb work when the node can't grant more
                    # workers (saturation)
                    idle_capacity = sum(
                        1 for l in pool.leases.values() if l.in_flight == 0
                    )
                    short = len(pool.queue) - idle_capacity
                    if not pool.queue or short <= 0:
                        pool.requesting = False
                        return
                want = max(1, min(short, rt.cfg.lease_request_batch))
                t_lease = time.monotonic()
                try:
                    reply = await self.noded.call(
                        "request_lease",
                        {"resources": pool.demand,
                         "env_hash": pool.env_hash,
                         "container": getattr(pool, "container", None),
                         "count": want},
                        timeout=60,
                    )
                except Exception as e:
                    logger.debug("lease request failed: %s", e)
                    rpc_failures += 1
                    # jittered backoff, not constant pacing: N shards
                    # retrying in lockstep against one wedged daemon
                    # would otherwise synchronize into request storms
                    await asyncio.sleep(backoff_delay_s(
                        rpc_failures, base_s=0.1, cap_s=2.0,
                        floor_s=0.05, rng=rt._retry_rng,
                    ))
                    continue
                rpc_failures = 0
                _mdefs.observe(
                    "rt_owner_lease_latency_seconds",
                    time.monotonic() - t_lease,
                    tags={"shard": str(self.index)},
                )
                grants, err = _parse_lease_reply(reply)
                if err == "env_error":
                    # the daemon cannot materialize this runtime env at
                    # all (e.g. container image with no podman/docker on
                    # the host): fail the queued tasks with the cause
                    # instead of retrying forever
                    self._fail_queue_env_error(pool, reply["env_error"])
                    return
                if err == "infeasible":
                    # local node can never host this demand: hand the
                    # queued tasks to the node daemon, whose queue path
                    # spills to a feasible node
                    with self.lock:
                        specs = list(pool.queue)
                        pool.queue.clear()
                        pool.requesting = False
                    for s in specs:
                        self.noded.send("submit_task", s)
                    return
                if not grants:
                    dry_rounds += 1
                    # saturated node (workers busy / spawn in flight):
                    # back off the poll instead of hammering the daemon
                    # at a fixed cadence from every shard at once
                    await asyncio.sleep(backoff_delay_s(
                        dry_rounds, base_s=0.02, cap_s=0.5,
                        floor_s=0.01, rng=rt._retry_rng,
                    ))
                    continue
                dry_rounds = 0
                _mdefs.inc("rt_owner_lease_grants_total", float(len(grants)),
                           tags={"shard": str(self.index)})
                for worker_id, socket_path in grants:
                    await self._adopt_grant(pool, worker_id, socket_path)
        except Exception:
            logger.exception("lease acquisition failed")
            with self.lock:
                pool.requesting = False

    def _fail_queue_env_error(self, pool: LeasePool, cause: str):
        from ray_tpu import exceptions as exc
        from ray_tpu.core import serialization as ser

        envelope = ser.serialize_to_bytes(
            exc.RayTpuError(f"runtime_env setup failed: {cause}"),
            tag=ser.TAG_ERROR,
        )
        with self.lock:
            specs = list(pool.queue)
            pool.queue.clear()
            pool.requesting = False
        for s in specs:
            self.rt._complete_task(TaskResult(
                task_id=s.task_id, status="error", error=envelope,
            ))

    async def _adopt_grant(self, pool: LeasePool, worker_id: str,
                           socket_path: str):
        """Connect one granted worker and drain queued work onto it."""
        breaker = rpc.breaker_for(f"lease:{socket_path}")
        if not breaker.allow():
            # a worker whose socket keeps failing: hand the lease back
            # and let the daemon grant another (paced so a re-grant of
            # the same worker can't spin this loop hot in the cooldown)
            self.noded.send("return_lease", {"worker_id": worker_id})
            await asyncio.sleep(0.05)
            return
        try:
            conn = await rpc.connect_unix(
                socket_path, handler=self.rt._handle,
                name=f"lease-{worker_id[:8]}",
            )
        except Exception as e:
            logger.debug("lease socket connect to %s failed: %s",
                         worker_id[:8], e)
            breaker.record_failure()
            self.noded.send("return_lease", {"worker_id": worker_id})
            return
        breaker.record_success()
        lease = Lease(worker_id, conn, socket_path=socket_path)
        with self.lock:
            pool.leases[worker_id] = lease
            self.conn_lease[conn] = (pool, lease)
        conn.on_close = self.on_lease_conn_closed
        self.drain_pool(pool, lease)
        # a grant that raced with the queue draining elsewhere must not
        # idle forever holding resources
        await self.maybe_return_lease(pool, lease)

    def drain_pool(self, pool: LeasePool, lease: Lease):
        while True:
            with self.lock:
                if not pool.queue or lease.in_flight >= PIPELINE_DEPTH:
                    return
                spec = pool.queue.popleft()
                lease.in_flight += 1
                lease.assigned[spec.task_id.binary()] = spec
            try:
                lease.conn.send_threadsafe("execute_task", spec)
            except rpc.ConnectionLost:
                return

    def on_lease_conn_closed(self, conn: rpc.Connection):
        with self.lock:
            entry = self.conn_lease.pop(conn, None)
            if entry is None:
                return
            pool, lease = entry
            pool.leases.pop(lease.worker_id, None)
            specs = list(lease.assigned.values())
        if lease.socket_path:
            # the worker is gone and its socket path won't be re-granted
            # (a replacement worker gets a fresh one): evict its breaker
            # so the board stays bounded under worker churn
            rpc.drop_breaker(f"lease:{lease.socket_path}")
        for spec in specs:
            self.rt._complete_task(
                TaskResult(task_id=spec.task_id, status="worker_died")
            )

    # ------------------------------------------------------------------
    # idle-lease return (shard loop)
    # ------------------------------------------------------------------
    async def maybe_return_lease(self, pool: LeasePool, lease: Lease):
        """Idle lease handling: keep the worker warm for a grace period
        so steady submit->get loops reuse it (conn and all) instead of
        paying a lease round trip per task; a delayed task returns it if
        still idle when the grace expires."""
        rt = self.rt
        with self.lock:
            idle = (
                not pool.queue
                and lease.in_flight == 0
                and pool.leases.get(lease.worker_id) is lease
            )
            if idle:
                lease.idle_token += 1
                token = lease.idle_token
        if not idle:
            return
        keepalive = rt.cfg.lease_keepalive_ms / 1000.0
        if keepalive > 0 and not rt._shutdown:
            timer = asyncio.ensure_future(
                self._return_lease_later(pool, lease, token, keepalive)
            )
            self.lease_timers.add(timer)
            timer.add_done_callback(self.lease_timers.discard)
        else:
            await self._return_lease_now(pool, lease)

    async def _return_lease_later(self, pool, lease, token, delay):
        await asyncio.sleep(delay)
        if self.rt._shutdown:
            return
        with self.lock:
            still_idle = (
                not pool.queue
                and lease.in_flight == 0
                and pool.leases.get(lease.worker_id) is lease
                and lease.idle_token == token  # no busy period since
            )
        if still_idle:
            await self._return_lease_now(pool, lease)

    async def _return_lease_now(self, pool: LeasePool, lease: Lease):
        with self.lock:
            # full re-verify under ONE critical section: between any
            # earlier idle check and this lock, a submitter may have
            # pushed work onto this lease — popping it then would sever
            # the in-flight task's result channel without the
            # on_lease_conn_closed recovery (its map entry would
            # already be gone)
            if (
                pool.leases.get(lease.worker_id) is not lease
                or lease.in_flight != 0
                or pool.queue
            ):
                return
            pool.leases.pop(lease.worker_id, None)
            self.conn_lease.pop(lease.conn, None)
        try:
            self.noded.send("return_lease", {"worker_id": lease.worker_id})
        except Exception as e:
            logger.debug("return_lease dropped: %s", e)
        await lease.conn.close()


def _parse_lease_reply(reply):
    """-> (grants, error_kind).  Accepts the batched `{"grants": [...]}`
    shape and the legacy single-grant tuple/None (a daemon one minor
    revision behind still interoperates)."""
    if reply is None:
        return [], None
    if isinstance(reply, dict):
        if reply.get("env_error"):
            return [], "env_error"
        if reply.get("infeasible"):
            return [], "infeasible"
        return [tuple(g) for g in reply.get("grants", [])], None
    return [tuple(reply)], None


def shard_index(task_id_bytes: bytes, n: int) -> int:
    """Task-id -> shard key.  The trailing bytes of a TaskID are random
    per task (ids.py), so a plain modulus balances without hashing."""
    if n <= 1:
        return 0
    return task_id_bytes[-1] % n
