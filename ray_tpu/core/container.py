"""Container runtime-env: run workers inside an image.

Reference: `python/ray/_private/runtime_env/image_uri.py:106`
(`ImageURIPlugin` — the runtime-env agent wraps the worker command in
`podman run` with the session dir and networking shared).  Here the
node daemon owns worker spawning, so the container wrapper is applied
at spawn synthesis time through an injectable `ContainerRuntime` seam
(mock in tests; podman/docker when present on the host).

runtime_env surface (either form):
    {"image_uri": "docker.io/org/img:tag"}
    {"container": {"image": "...", "run_options": ["--cap-add=..."],
                   "python": "/usr/bin/python3"}}

Workers spawned for a container env are DEDICATED to its env hash: a
plain worker can never serve a containerized env (there is no way to
enter an image from inside an already-running process), and the
scheduler only matches exact env hashes for such demands.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
from typing import Any, Dict, List, Optional

CONTAINER_KEYS = ("image_uri", "container")


def container_section(renv: Optional[Dict[str, Any]]) -> Optional[Dict]:
    """Normalized container spec from a runtime env, or None.
    `image_uri` is sugar for `{"container": {"image": ...}}`."""
    if not renv:
        return None
    if renv.get("image_uri") and renv.get("container"):
        raise ValueError(
            "runtime_env cannot set both 'image_uri' and 'container'"
        )
    if renv.get("image_uri"):
        return {"image": renv["image_uri"]}
    c = renv.get("container")
    if not c:
        return None
    if not isinstance(c, dict) or not c.get("image"):
        raise ValueError(
            "runtime_env['container'] must be a dict with an 'image'"
        )
    if not isinstance(c["image"], str):
        raise ValueError("container 'image' must be a string")
    opts = c.get("run_options") or []
    # a bare string would explode into characters; non-strings would
    # fail deep inside the daemon's spawn, leaking its pending slot
    if (not isinstance(opts, (list, tuple))
            or not all(isinstance(o, str) for o in opts)):
        raise ValueError(
            "container 'run_options' must be a list of strings"
        )
    python = c.get("python") or "python3"
    if not isinstance(python, str):
        raise ValueError("container 'python' must be a string")
    return {
        "image": c["image"],
        "run_options": list(opts),
        "python": python,
    }


class ContainerRuntime:
    """Synthesizes the argv that runs a worker inside a container.
    Injectable seam (reference: the podman command assembly in
    `image_uri.py`); `available()` gates scheduling-time validation."""

    def available(self) -> bool:
        raise NotImplementedError

    def synthesize(self, spec: Dict[str, Any], inner_argv: List[str],
                   env: Dict[str, str],
                   mounts: List[str]) -> List[str]:
        raise NotImplementedError

    def kill_booting(self, token: str) -> None:
        """Best-effort kill of a spawned-but-unregistered worker; the
        default (host-exec fakes) needs nothing beyond the client
        SIGKILL the daemon already sends."""


class DefaultContainerRuntime(ContainerRuntime):
    """podman preferred, docker fallback (reference: podman in
    `image_uri.py`, docker via the cluster-launcher path)."""

    def __init__(self):
        self._exe = shutil.which("podman") or shutil.which("docker")

    def available(self) -> bool:
        return self._exe is not None

    def synthesize(self, spec, inner_argv, env, mounts):
        if not self._exe:
            raise RuntimeError(
                "no container runtime on PATH (podman/docker) for "
                f"image {spec.get('image')!r}"
            )
        # host namespaces: the daemon addresses workers by pid (boot
        # accounting, shm creator reaping) and shares unix sockets and
        # /dev/shm segments with them — an isolated pid/ipc/net
        # namespace would break all three
        argv = [self._exe, "run", "--rm", "--network=host",
                "--ipc=host", "--pid=host"]
        token = env.get("RT_SPAWN_TOKEN")
        if token:
            # a deterministic name so a hung boot can be killed: SIGKILL
            # on the podman CLIENT would strand the container
            argv += ["--name", f"rtw-{token}"]
        for m in mounts:
            argv += ["-v", f"{m}:{m}"]
        for k, v in sorted(env.items()):
            argv += ["--env", f"{k}={v}"]
        argv += list(spec.get("run_options") or ())
        argv.append(spec["image"])
        python = spec.get("python") or "python3"
        # inner_argv is [sys.executable, "-m", ...]: swap in the
        # image's interpreter
        argv += [python] + list(inner_argv[1:])
        return argv


    def kill_booting(self, token: str) -> None:
        """Terminate a named still-booting container (the boot-deadline
        path: killing the client process does not kill the container)."""
        if self._exe and token:
            import subprocess

            subprocess.Popen(
                [self._exe, "kill", f"rtw-{token}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )


class RecordingFakeRuntime(ContainerRuntime):
    """Test double: records what WOULD run (JSON lines at `log_path`)
    and execs the worker directly on the host so clusters in images
    without podman still exercise the full spawn/dedication path."""

    def __init__(self, log_path: str):
        self.log_path = log_path
        self._real = DefaultContainerRuntime()

    def available(self) -> bool:
        return True

    def synthesize(self, spec, inner_argv, env, mounts):
        record = {
            "image": spec.get("image"),
            "run_options": spec.get("run_options") or [],
            "env": dict(env),
            "mounts": list(mounts),
            "argv": (self._real.synthesize(spec, inner_argv, env, mounts)
                     if self._real.available() else None),
        }
        with open(self.log_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        return list(inner_argv)


_runtime: Optional[ContainerRuntime] = None


def set_container_runtime(runtime: Optional[ContainerRuntime]) -> None:
    global _runtime
    _runtime = runtime


def get_container_runtime() -> ContainerRuntime:
    """Process-wide container runtime; `RT_CONTAINER_FAKE_LOG` installs
    the recording fake (inherited by spawned daemons, so tests can
    assert command synthesis across processes)."""
    global _runtime
    if _runtime is None:
        fake = os.environ.get("RT_CONTAINER_FAKE_LOG")
        _runtime = (RecordingFakeRuntime(fake) if fake
                    else DefaultContainerRuntime())
    return _runtime
