"""Task-event buffering: the observability feed.

Reference: `src/ray/core_worker/task_event_buffer.h:220` — every runtime
buffers per-task state transitions locally and flushes them to the
control plane in periodic batches (never on the hot path), where the
GCS-task-manager-equivalent keeps a bounded ring the state API and
timeline read from (`gcs_task_manager.h`, `util/state/api.py`).

Bounded with eviction accounting: when the buffer is full, the OLDEST
buffered event is evicted (the freshest state transition is the one the
dashboard needs), every eviction is counted, and the count surfaces
both as a `__dropped__` marker event in the next drain and as the
`rt_task_events_dropped_total` metric — a flush loop that cannot keep
up is itself observable.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

FLUSH_PERIOD_S = 0.5
MAX_BUFFER = 10_000


class TaskEventBuffer:
    def __init__(self, max_buffer: int = 0):
        self._lock = threading.Lock()
        self._max = int(max_buffer) if max_buffer and max_buffer > 0 \
            else MAX_BUFFER
        self._events: deque = deque()
        self._dropped = 0
        self._dropped_total = 0  # monotonic, for tests/introspection

    def record(self, task_id: bytes, name: str, state: str,
               node_id: str = "", worker_id: str = "",
               error: str = "", duration: Optional[float] = None):
        ev = {
            "task_id": task_id.hex(),
            "name": name,
            "state": state,  # SUBMITTED | RUNNING | FINISHED | FAILED
            "ts": time.time(),
        }
        if node_id:
            ev["node_id"] = node_id
        if worker_id:
            ev["worker_id"] = worker_id
        if error:
            ev["error"] = error[:512]
        if duration is not None:
            ev["duration"] = duration
        with self._lock:
            if len(self._events) >= self._max:
                # evict oldest: under sustained overload the window
                # slides forward instead of freezing at the first
                # MAX_BUFFER events
                self._events.popleft()
                self._dropped += 1
                self._dropped_total += 1
            self._events.append(ev)

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return self._dropped_total

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
            self._events.clear()
            dropped, self._dropped = self._dropped, 0
        if dropped:
            # ONE metric touch per flush, not per evicted event: under
            # sustained overload every record() hits the drop path, so
            # a per-event inc would tax exactly the storm being observed
            from ray_tpu.metrics import metric_defs as _md

            _md.metric("rt_task_events_dropped_total").inc(dropped)
            out.append({
                "task_id": "", "name": "__dropped__", "state": "DROPPED",
                "ts": time.time(), "count": dropped,
            })
        return out
