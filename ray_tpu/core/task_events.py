"""Task-event buffering: the observability feed.

Reference: `src/ray/core_worker/task_event_buffer.h:220` — every runtime
buffers per-task state transitions locally and flushes them to the
control plane in periodic batches (never on the hot path), where the
GCS-task-manager-equivalent keeps a bounded ring the state API and
timeline read from (`gcs_task_manager.h`, `util/state/api.py`).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

FLUSH_PERIOD_S = 0.5
MAX_BUFFER = 10_000


class TaskEventBuffer:
    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0

    def record(self, task_id: bytes, name: str, state: str,
               node_id: str = "", worker_id: str = "",
               error: str = "", duration: Optional[float] = None):
        ev = {
            "task_id": task_id.hex(),
            "name": name,
            "state": state,  # SUBMITTED | RUNNING | FINISHED | FAILED
            "ts": time.time(),
        }
        if node_id:
            ev["node_id"] = node_id
        if worker_id:
            ev["worker_id"] = worker_id
        if error:
            ev["error"] = error[:512]
        if duration is not None:
            ev["duration"] = duration
        with self._lock:
            if len(self._events) >= MAX_BUFFER:
                self._dropped += 1
                return
            self._events.append(ev)

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
            dropped, self._dropped = self._dropped, 0
        if dropped:
            out.append({
                "task_id": "", "name": "__dropped__", "state": "DROPPED",
                "ts": time.time(), "count": dropped,
            })
        return out
