"""Runtime configuration table.

One declarative table of tunables, every entry overridable by an
``RT_<NAME>`` environment variable — the same single-source-of-truth shape
as the reference's ``RAY_CONFIG`` macro table
(`src/ray/common/ray_config_def.h`, 217 entries, env-overridable) without
the C++ preprocessor.  Processes spawned by the runtime inherit overrides
through the environment, and ``init(_system_config=...)`` can override
programmatically (forwarded to children like `services.py` does).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict

_ENV_PREFIX = "RT_"


def _coerce(raw: str, typ):
    if typ is bool:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if typ is int:
        return int(raw)
    if typ is float:
        return float(raw)
    if typ is dict:
        return json.loads(raw)
    return raw


@dataclass
class Config:
    # ---- object store ------------------------------------------------
    #: bytes; default sized at init time from system memory if 0
    object_store_memory: int = 0
    #: objects <= this many bytes are returned inline in the RPC reply
    #: and live in the owner's in-process store (reference: direct
    #: returns via the core-worker memory store).
    max_direct_call_object_size: int = 100 * 1024
    #: chunk size for node-to-node object transfer (reference default
    #: 5 MiB, `ray_config_def.h` object_manager_default_chunk_size).
    object_transfer_chunk_bytes: int = 5 * 1024 * 1024
    #: fraction of store capacity above which eviction kicks in
    object_store_eviction_watermark: float = 1.0
    #: end-to-end object integrity: checksum at spill/transfer source,
    #: verify on restore-from-spill and node-to-node receive (mismatch
    #: -> quarantine / re-fetch, then treat-as-lost so lineage
    #: re-derives).  The spill-path cost is one CRC pass per object
    #: (measured ≤5%: PERF.md data_shuffle integrity on/off row).
    object_integrity: bool = True
    #: ALSO verify on local shm get (hot path; opt-in — a local read
    #: of a sealed shm segment is not a storage fault domain)
    object_integrity_verify_get: bool = False
    #: stop ELECTING new spills when the spill filesystem has less
    #: than this many bytes free — backpressure surfaces as a typed
    #: BackPressureError at the producer instead of an ENOSPC crash
    #: mid-write when the disk is actually full
    spill_disk_min_free_bytes: int = 64 * 1024 * 1024
    #: attempts per spill-restore disk read before the restore is
    #: declared failed and the object falls back to lineage
    #: reconstruction (EIO is often transient; each retry backs off
    #: through core/retry.py's jittered schedule)
    disk_io_retries: int = 3

    # ---- scheduling --------------------------------------------------
    #: FLOOR of the retry backoff schedule (legacy knob; reference
    #: task_retry_delay_ms, `ray_config_def.h:410`).  Retries now pace
    #: with capped exponential backoff + full jitter (core/retry.py);
    #: this keeps its historical meaning as the minimum delay.
    task_retry_delay_ms: int = 0
    #: default max retries for tasks (reference default 3)
    task_max_retries: int = 3
    #: backoff base: retry k sleeps uniform(0, min(cap, base * 2**k))
    task_retry_backoff_base_ms: int = 50
    #: backoff cap: no single retry waits longer than this
    task_retry_backoff_max_ms: int = 5000
    #: retry-budget bucket size (tokens; one retry spends one token).
    #: Bounds the retry BURST under correlated failures — when the
    #: bucket drains, failures go final instead of resubmitting.
    task_retry_budget_cap: float = 64.0
    #: tokens refilled per successful task completion (caps steady-state
    #: retry amplification at this fraction of the success rate)
    task_retry_budget_refill: float = 0.5
    #: ship worker task/actor prints to the owning driver's stderr
    #: (reference: log_monitor.py tail -> driver stdout); files under
    #: the session dir remain the durable copy either way
    log_to_driver: bool = True
    #: refuse pickled (non-schema) control frames: only the wire codec
    #: (`core/wire.py`) is accepted on this process's connections
    #: (RT_WIRE_REQUIRE_SCHEMA=1; reference analog: protobuf-only
    #: services — `src/ray/protobuf/`)
    wire_require_schema: bool = False
    #: workers prestarted per node at init; 0 = num_cpus
    num_workers_per_node: int = 0
    #: soft cap on lease pipelining per worker
    max_tasks_in_flight_per_worker: int = 64
    #: how long an idle leased worker is kept before being returned to
    #: the node daemon; steady submit->get loops reuse the warm worker
    #: + conn instead of paying a lease round trip per task (reference:
    #: idle worker caching in the worker pool rather than instant
    #: return, `worker_pool.h` idle policy)
    lease_keepalive_ms: int = 500
    #: driver-side owner shards (RT_OWNER_SHARDS).  1 = the classic
    #: single-owner plane (everything on the runtime's io loop).  N>1
    #: splits task-lifecycle submission/completion across N event loops
    #: on N threads, each with its own node-daemon connection and lease
    #: pools, keyed by task id — the driver plane then scales with
    #: cores instead of one asyncio loop (reference analog: the
    #: GCS/raylet split that lets the reference drain 1M queued tasks
    #: across 64 cores; see docs/control_plane.md).
    owner_shards: int = 1
    #: max lease grants asked of the node daemon in ONE request_lease
    #: round — a submission burst amortizes lease negotiation over a
    #: batch instead of one RPC per worker grant
    lease_request_batch: int = 16
    #: top-k fraction for hybrid scheduling randomization (reference
    #: hybrid policy top-k, `hybrid_scheduling_policy.h:50`)
    scheduler_top_k_fraction: float = 0.2
    #: pack threshold before spilling to other nodes (reference
    #: scheduler_spread_threshold)
    scheduler_spread_threshold: float = 0.5

    # ---- compiled DAGs (ray_tpu/dag/) --------------------------------
    #: slots per compiled-DAG channel ring (RT_DAG_RING_SLOTS): how many
    #: in-flight messages a channel buffers before writers block.  Both
    #: endpoints must see the same value (it propagates through the
    #: environment like every knob); the CREATING process's geometry
    #: wins for a ring that already exists.
    dag_ring_slots: int = 8
    #: inline payload budget per ring slot (RT_DAG_SLOT_BYTES); larger
    #: payloads spill to one store object per message with only the key
    #: in the slot
    dag_slot_bytes: int = 128 * 1024

    # ---- memory monitor / OOM killer ---------------------------------
    #: period between node memory polls; 0 disables the monitor
    #: (reference memory_monitor_refresh_ms, `ray_config_def.h`)
    memory_monitor_refresh_ms: int = 1000
    #: node memory fraction above which a busy task worker is killed
    #: instead of risking the kernel OOM killer (reference
    #: memory_usage_threshold)
    memory_usage_threshold: float = 0.97
    #: victim selection: retriable_lifo | group_by_owner (reference
    #: worker_killing_policy.h:34)
    worker_killing_policy: str = "retriable_lifo"

    # ---- health / fault tolerance ------------------------------------
    #: period between controller->node health probes (reference
    #: health_check_period_ms, `ray_config_def.h:843`)
    health_check_period_ms: int = 1000
    #: probes missed before a node is declared dead
    health_check_failure_threshold: int = 5
    #: max actor restarts when not specified per-actor
    actor_max_restarts: int = 0
    #: controller durable-state backend URL: "" = session-local file;
    #: "sqlite:///path/state.db" for the database tier, "memory://" to
    #: disable durability entirely (no persist loop) (reference: in-memory vs Redis StoreClient
    #: choice, `redis_store_client.h:106`)
    controller_store_url: str = ""
    #: address the node daemon + controller TCP servers bind.  The
    #: default keeps single-host clusters loopback-only; multi-host
    #: TPU-VM clusters set RT_BIND_HOST=0.0.0.0 in the bootstrap
    #: script so workers on other hosts can join.
    bind_host: str = "127.0.0.1"
    #: address ADVERTISED to peers (node registration, controller
    #: address).  Empty = the bind host, or the primary interface IP
    #: when binding 0.0.0.0.
    advertise_host: str = ""
    #: fixed TCP port for the controller (0 = ephemeral).  A pinned
    #: port is what lets worker daemons reconnect to a RESTARTED head
    #: (reference: raylets reconnect to the GCS at its known address,
    #: `gcs_redis_failure_detector.h`)
    controller_port: int = 0
    #: how long a worker daemon keeps retrying the controller before
    #: giving up and exiting (reference: `ray_config_def.h`
    #: gcs_rpc_server_reconnect_timeout_s)
    controller_reconnect_timeout_s: float = 60.0
    #: per-peer-address circuit breaker (core/rpc.py): consecutive
    #: connection failures before the breaker opens and the address is
    #: skipped by reconnect/lease/router paths
    breaker_failure_threshold: int = 5
    #: how long an open breaker rejects before allowing a half-open
    #: probe toward the address
    breaker_cooldown_s: float = 2.0

    # ---- rpc ---------------------------------------------------------
    #: max message size on the control plane
    rpc_max_message_bytes: int = 512 * 1024 * 1024
    #: driver/worker connection timeout
    rpc_connect_timeout_s: float = 30.0

    # ---- metrics / events --------------------------------------------
    #: cadence of the batched obs frames (metrics snapshot + finished
    #: spans) every process ships to the controller — one frame per
    #: process per interval, never a per-sample RPC
    metrics_report_interval_ms: int = 2000
    task_events_buffer_size: int = 10000
    #: core-path metric instrumentation (owner-plane histograms,
    #: shuffle/train counters) + the metrics half of the obs frames.
    #: OFF by default — the disabled record helpers cost one bool test
    #: (measured <3% storm overhead even ON: `perf.py --config
    #: obs_overhead`, PERF.md).  RT_METRICS_ENABLED propagates to
    #: children like the tracing flag.
    metrics_enabled: bool = False
    #: Prometheus `/metrics` HTTP listener on each node daemon.
    #: 0 = disabled (default).  A positive port is bound by the HEAD
    #: daemon (worker daemons take an ephemeral port so one host can
    #: run many); negative = ephemeral everywhere.  The bound port is
    #: advertised in node registration (`get_nodes` → "metrics_port").
    metrics_http_port: int = 0

    # ---- sanitizer (ray_tpu/util/sanitizer.py, RT_SANITIZE=1) --------
    #: event-loop lag watchdog threshold: a single callback holding a
    #: registered loop longer than this many ms is reported (with the
    #: offending callable) when the sanitizer is on; 0 disables the
    #: watchdog while keeping lock-order/leak checks
    sanitize_loop_lag_ms: float = 500.0

    # ---- paths -------------------------------------------------------
    session_dir: str = ""  # filled at init: /tmp/ray_tpu/session_<ts>

    def apply_env_overrides(self) -> "Config":
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is not None:
                setattr(self, f.name, _coerce(env, f.type if isinstance(f.type, type) else type(getattr(self, f.name))))
        return self

    def apply_dict(self, overrides: Dict[str, Any]) -> "Config":
        known = {f.name for f in fields(self)}
        for k, v in overrides.items():
            if k not in known:
                raise ValueError(f"unknown config key: {k}")
            setattr(self, k, v)
        return self

    def to_env(self) -> Dict[str, str]:
        """Serialize every non-default entry as RT_* env vars so spawned
        processes (node daemons, workers) see the same config."""
        out = {}
        default = Config()
        for f in fields(self):
            v = getattr(self, f.name)
            if v != getattr(default, f.name):
                out[_ENV_PREFIX + f.name.upper()] = (
                    json.dumps(v) if isinstance(v, dict) else str(v)
                )
        return out


_global: Config | None = None


def get_config() -> Config:
    global _global
    if _global is None:
        _global = Config().apply_env_overrides()
    return _global


def set_config(cfg: Config) -> None:
    global _global
    _global = cfg
