"""Process-spawn environment hygiene.

The deployment image's sitecustomize registers the TPU PJRT plugin —
importing jax — in EVERY interpreter whose env carries the axon pool
marker.  That is a ~10s (worse under load) import tax per process, paid
even by infrastructure daemons that never touch jax.  Node daemons
always strip it; worker processes keep it unless the session is pinned
to CPU (tests), since workers may execute TPU compute.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

_AXON_MARKER = "PALLAS_AXON_POOL_IPS"
_STASH = "RT_STASHED_AXON_POOL_IPS"


def infra_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env for spawning a node daemon: the axon marker is stashed so the
    daemon itself skips the jax-importing sitecustomize path but can
    still hand it back to workers."""
    env = dict(base if base is not None else os.environ)
    marker = env.pop(_AXON_MARKER, None)
    if marker:
        env[_STASH] = marker
    return env


def adopt_sys_path(paths) -> bool:
    """Prepend the driver's sys.path entries (those that exist here and
    aren't present yet), preserving their order.  Shared by the
    spawn-env path (worker_main) and the KV retry path (runtime) so the
    adoption policy cannot diverge.  Returns True if anything was
    added."""
    import sys

    added = False
    for p in reversed(list(paths)):
        if p and p not in sys.path and os.path.isdir(p):
            sys.path.insert(0, p)
            added = True
    return added


def worker_env(base: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Env for spawning a worker: restore the axon marker unless the
    session runs on CPU (JAX_PLATFORMS=cpu — the test configuration),
    where the TPU plugin import would be pure overhead."""
    env = dict(base if base is not None else os.environ)
    stashed = env.pop(_STASH, None)
    if stashed and env.get("JAX_PLATFORMS", "").lower() != "cpu":
        env[_AXON_MARKER] = stashed
    return env
