"""Node daemon: the per-node runtime (raylet equivalent).

One per node (reference: `src/ray/raylet/node_manager.h:119`).  Owns:

- the worker pool: prestart, spawn-on-demand, death detection
  (reference: `worker_pool.h:174`),
- the local scheduler: FIFO-with-window dispatch against node resources,
  worker leases with in-lease pipelining, spillback to other nodes via
  the controller (reference: `cluster_task_manager.h:42`,
  `local_task_manager.h:58`, lease pipelining in
  `normal_task_submitter.h:75`),
- message routing between workers/drivers across nodes (the owner
  protocol rides this),
- node-to-node object transfer in/out of the shm store (reference:
  `object_manager.h:117` chunked push/pull),
- the shm store segment lifecycle for the node.

The head daemon also hosts the Controller service on its TCP port
(the reference colocates GCS on the head node).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from ray_tpu.core import accelerators, diskio as _diskio, rpc
from ray_tpu.core import integrity as _integrity
from ray_tpu.core.config import Config, get_config
from ray_tpu.metrics import metric_defs as _md
from ray_tpu.core.ids import NodeID
from ray_tpu.core.task_spec import ActorCreationSpec, Resources, SchedulingStrategy, TaskResult, TaskSpec, fits as _fits, match_labels
from ray_tpu.shm import ObjectExistsError, ShmStore

logger = logging.getLogger(__name__)

_PIPELINE_DEPTH = 4  # tasks pushed to one leased worker ahead of completion


def _fault_metric(name: str, tags=None, value: float = 1.0):
    """Integrity/storage-fault counters bypass the metrics_enabled
    gate: they record rare failure events, not hot-path samples, and
    the chaos acceptance tests read them with instrumentation off."""
    try:
        _md.metric(name).inc(value, tags=tags)
    except Exception:  # metrics must never break a fault path
        logger.debug("fault metric %s failed", name, exc_info=True)


@dataclass
class _SpillEntry:
    """One disk-spilled primary copy: where it lives and the checksum
    its bytes carried when they left the shm store (the spill
    manifest; reference: `local_object_manager.h:41` url_with_offset
    records).  A JSON sidecar (`<path>.meta`) mirrors this entry for
    diagnostics and for verification after the in-memory index is
    gone."""

    path: str
    size: int
    crc: Optional[int] = None
    algo: Optional[str] = None


@dataclass
class WorkerState:
    worker_id: str
    pid: int
    conn: Optional[rpc.Connection] = None
    kind: str = "worker"  # worker | driver
    socket_path: Optional[str] = None  # worker's own server socket
    actor_id: Optional[bytes] = None
    lease: Optional[Dict[str, float]] = None  # charged resources
    leased_to: Optional[str] = None  # worker_id of the lease holder
    in_flight: Dict[bytes, TaskSpec] = field(default_factory=dict)
    proc: Optional[subprocess.Popen] = None
    busy_since: Optional[float] = None  # OOM victim ordering (LIFO)
    oom_killed_at: Optional[float] = None  # SIGKILL sent; awaiting reap
    # runtime-env dedication: once a worker applies an env it serves
    # ONLY that env hash (reference: worker-pool runtime-env matching);
    # clean tasks never run on a tainted worker
    env_hash: Optional[str] = None
    # mid-task get() is parked on an unavailable object: its lease
    # CPUs are RELEASED back to the node (reference: blocked-worker
    # accounting in the raylet — `node_manager.cc` HandleTaskBlocked)
    # so dependency-producing work can run.  Without this, lineage
    # reconstruction deadlocks the moment every worker slot holds a
    # consumer blocked on an object only a queued task can re-derive.
    blocked: bool = False

    @property
    def idle(self):
        return (
            not self.in_flight
            and self.actor_id is None
            and self.leased_to is None
        )


class NodeDaemon:
    def __init__(self, session_dir: str, is_head: bool, controller_addr=None,
                 num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
                 resources: Optional[Dict[str, float]] = None,
                 num_workers: int = 0, node_name: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.cfg: Config = get_config()
        self.session_dir = session_dir
        self.is_head = is_head
        self.node_id = NodeID.random().hex()
        self.node_name = node_name or self.node_id[:8]
        # the ".<pid>" suffix marks this daemon as the segment's owner
        # so a later boot can reap it if we die without unlinking
        # (shm.sweep_stale_segments)
        self.shm_name = (
            f"/rt_{os.path.basename(session_dir)}_{self.node_id[:8]}"
            f".{os.getpid()}"
        )
        self.socket_path = os.path.join(session_dir, f"noded_{self.node_id[:8]}.sock")

        ncpu = num_cpus if num_cpus is not None else float(os.cpu_count() or 4)
        self.total_resources: Dict[str, float] = {"CPU": ncpu}
        if num_tpus is None:
            # autodetect local chips (reference: accelerator managers
            # run at node start, `_private/accelerators/tpu.py:102`)
            detected = accelerators.detect_num_chips()
            if detected:
                num_tpus = float(detected)
        if num_tpus:
            self.total_resources["TPU"] = float(num_tpus)
        self.node_labels: Dict[str, str] = dict(labels or {})
        self._chip_pool: Optional[accelerators.ChipPool] = None
        if num_tpus and num_tpus >= 1 and float(num_tpus).is_integer():
            extra_res, tpu_labels = accelerators.node_tpu_extras(int(num_tpus))
            for k, v in extra_res.items():
                self.total_resources.setdefault(k, v)
            for k, v in tpu_labels.items():
                self.node_labels.setdefault(k, v)
            self._chip_pool = accelerators.ChipPool(int(num_tpus))
        self.total_resources.update(resources or {})
        self.available = dict(self.total_resources)

        self.num_workers = num_workers or int(ncpu)
        self.store: Optional[ShmStore] = None
        self.workers: Dict[str, WorkerState] = {}  # worker_id -> state
        self._booting_tokens: set = set()  # spawn tokens not yet registered
        self._conn_worker: Dict[rpc.Connection, str] = {}
        # actor_id -> (ActorCreationSpec, worker_id) for actors this
        # node hosts — re-reported to a restarted controller so the
        # registry heals (re-adoption)
        self._hosted_actors: Dict[bytes, Tuple[Any, str]] = {}
        self.task_queue: Deque[TaskSpec] = deque()
        self.controller_addr = controller_addr
        self.controller_conn: Optional[rpc.Connection] = None
        self.controller = None  # Controller object when head
        self._node_conns: Dict[str, rpc.Connection] = {}  # node_id -> conn
        self._node_addrs: Dict[str, Tuple[str, int]] = {}
        self._pulls: Dict[bytes, asyncio.Future] = {}
        # inbound-transfer admission (reference: pull_manager.h:92)
        self._inflight_pull_bytes = 0
        self._pull_cv: Optional[asyncio.Condition] = None
        self._chan_pool = None  # dedicated pool for blocking ring writes
        # disk-spilled primary copies: id -> _SpillEntry (reference:
        # `local_object_manager.h:41` spilling/restoring)
        self._spilled: Dict[bytes, _SpillEntry] = {}
        self._spill_dir = os.path.join(session_dir, "spilled")
        self._quarantine_dir = os.path.join(self._spill_dir, "quarantine")
        # low-disk latch: set when the spill filesystem is below the
        # free-bytes watermark (or a write hit real ENOSPC); spill_now
        # replies carry it so producers clamp with a typed
        # BackPressureError instead of spinning against a full disk
        self._spill_disk_full = False
        import threading as _threading

        # spill/restore mutate the store + index from the executor
        # thread (file IO must not stall the io loop — the reference
        # uses dedicated IO workers the same way)
        # REENTRANT: a restore under pressure force-spills other
        # objects while already holding the lock
        self._spill_lock = _threading.RLock()
        self._actor_locations: Dict[bytes, Tuple[str, str]] = {}
        self.unix_server: Optional[rpc.Server] = None
        self.tcp_server: Optional[rpc.Server] = None
        self.tcp_port: int = 0
        self.controller_port: int = 0
        # Prometheus /metrics listener (cfg.metrics_http_port); the
        # bound port is advertised in node registration
        self._metrics_server = None
        self.metrics_http_port: int = 0

    # ------------------------------------------------------------------
    # startup
    # ------------------------------------------------------------------
    async def start(self):
        # daemon boot doubles as the host's janitor: segments owned by
        # hard-killed sessions would otherwise eat /dev/shm forever
        from ray_tpu import shm as _shm

        _shm.sweep_stale_segments()
        cap = self.cfg.object_store_memory
        if cap <= 0:
            cap = _default_store_capacity()
        self.store = ShmStore(self.shm_name, capacity=cap, create=True)

        self.unix_server = rpc.Server(self, name=f"noded-{self.node_name}-unix")
        await self.unix_server.start_unix(self.socket_path)
        self.tcp_server = rpc.Server(self, name=f"noded-{self.node_name}-tcp")
        bind = self.cfg.bind_host or "127.0.0.1"
        self._advertise = self.cfg.advertise_host or (
            _primary_ip() if bind == "0.0.0.0" else bind
        )
        self.tcp_port = await self.tcp_server.start_tcp(bind, 0)

        if self.is_head:
            from ray_tpu.core.controller import Controller
            from ray_tpu.core.placement import PlacementGroupManager

            # operators pick the durability tier via the store URL
            # (sqlite:///..., memory://, a file path); default = a
            # session-local file (reference: in-memory vs Redis
            # StoreClient choice at GCS boot)
            self.controller = Controller(
                persist_path=self.cfg.controller_store_url or os.path.join(
                    self.session_dir, "controller_state.json"
                )
            )
            self.controller.load_persisted()
            self.controller._pg_manager = PlacementGroupManager(self.controller)
            ctl_server = rpc.Server(self.controller, name="controller")
            self.controller_port = await ctl_server.start_tcp(
                bind, self.cfg.controller_port
            )
            self._ctl_server = ctl_server
            self.controller.start_health_checks()
            self.controller_addr = (self._advertise, self.controller_port)

        if self.cfg.metrics_http_port != 0:
            await self._start_metrics_http(bind)

        # register with the controller like any node
        await self._connect_controller()
        for _ in range(self.num_workers):
            self._spawn_worker()
        asyncio.ensure_future(self._retry_queue_loop())
        asyncio.ensure_future(self._obs_report_loop())
        if self.cfg.memory_monitor_refresh_ms > 0:
            asyncio.ensure_future(self._memory_monitor_loop())
        logger.info(
            "noded %s up: %d workers, resources=%s",
            self.node_name,
            self.num_workers,
            self.total_resources,
        )

    async def _connect_controller(self):
        """Connect + register with the controller; arms the reconnect
        handler so a worker daemon survives a head/controller restart
        (reference: raylets reconnect to a restarted GCS and the
        cluster keeps running through the downtime,
        `gcs_redis_failure_detector.h` + test_gcs_fault_tolerance)."""
        self.controller_conn = await rpc.connect_tcp(
            *self.controller_addr, handler=self._ctl_push,
            name="noded->controller",
        )
        if not self.is_head:
            self.controller_conn.on_close = self._on_controller_lost
        await self.controller_conn.call(
            "register_node",
            {
                "node_id": self.node_id,
                "addr": (self._advertise, self.tcp_port),
                "resources": dict(self.total_resources),
                "is_head": self.is_head,
                "labels": dict(self.node_labels),
                "metrics_port": self.metrics_http_port,
            },
        )
        # re-adopt: tell the (possibly restarted) controller which
        # actors this node already hosts, so the registry and named
        # lookups heal without restarting user state
        for aid, (aspec, worker_id) in list(self._hosted_actors.items()):
            if worker_id in self.workers:
                try:
                    reply = await self.controller_conn.call(
                        "readopt_actor",
                        {"spec": aspec, "node_id": self.node_id,
                         "worker_id": worker_id},
                    )
                except Exception:
                    logger.exception("actor re-adoption failed")
                    continue
                if not reply.get("ok") and reply.get("action") == "kill":
                    # the controller failed this actor over during the
                    # disconnect — this copy is stale and must not keep
                    # running beside its replacement
                    logger.warning(
                        "killing stale actor copy %s (superseded during "
                        "controller disconnect)", aspec.actor_id.hex()[:8],
                    )
                    self._hosted_actors.pop(aid, None)
                    w = self.workers.get(worker_id)
                    if w is not None:
                        # unlink the actor BEFORE the kill: the exit
                        # handler must not report an actor death for a
                        # copy the controller already replaced
                        w.actor_id = None
                        try:
                            os.kill(w.pid, signal.SIGKILL)
                        except ProcessLookupError:
                            pass
        # force the next load report to be a FULL snapshot: the new
        # controller has no delta base
        self._last_load_report = None

    def _on_controller_lost(self, conn):
        if self._draining:
            return
        logger.warning("controller connection lost; reconnecting")
        asyncio.ensure_future(self._reconnect_controller())

    async def _reconnect_controller(self):
        deadline = time.monotonic() + self.cfg.controller_reconnect_timeout_s
        while time.monotonic() < deadline:
            if self._draining:
                return
            try:
                await self._connect_controller()
                logger.info("reconnected to controller")
                return
            except Exception as e:
                logger.debug("controller reconnect attempt failed: %s", e)
                await asyncio.sleep(1.0)
        logger.error(
            "controller unreachable for %.0fs; daemon shutting down",
            self.cfg.controller_reconnect_timeout_s,
        )
        os.kill(os.getpid(), signal.SIGTERM)

    async def _ctl_push(self, method, payload, conn):
        if method == "ping":
            return "pong"
        if method == "host_actor":
            return await self.handle_host_actor(payload, conn)
        if method == "kill_worker":
            return await self.handle_kill_worker(payload, conn)
        raise rpc.RpcError(f"noded: unexpected controller push {method!r}")

    def write_ready_file(self, path: str):
        with open(path + ".tmp", "w") as f:
            json.dump(
                {
                    "node_id": self.node_id,
                    "socket_path": self.socket_path,
                    "controller_addr": list(self.controller_addr),
                    "tcp_port": self.tcp_port,
                    "shm_name": self.shm_name,
                },
                f,
            )
        os.replace(path + ".tmp", path)

    # ------------------------------------------------------------------
    # worker pool (reference: worker_pool.h:174)
    # ------------------------------------------------------------------
    _pending_spawns = 0

    def _spawn_worker(self, container: Optional[tuple] = None) -> None:
        """`container=(env_hash, spec)` spawns the worker INSIDE the
        image via the injectable container runtime (reference:
        `runtime_env/image_uri.py:106` — the worker command wrapped in
        `podman run` with session dir and networking shared); such
        workers register pre-dedicated to their env hash."""
        from ray_tpu.core.env_utils import worker_env

        if logger.isEnabledFor(logging.DEBUG):
            import traceback

            caller = traceback.extract_stack(limit=2)[0]
            logger.debug(
                "spawn_worker pending=%d pool=%d from %s:%d",
                self._pending_spawns, len(self.workers),
                caller.name, caller.lineno,
            )
        self._pending_spawns += 1
        env = worker_env()
        env.update(self.cfg.to_env())
        env["RT_NODE_SOCKET"] = self.socket_path
        env["RT_CONTROLLER"] = f"{self.controller_addr[0]}:{self.controller_addr[1]}"
        # spawn tokens (not pids) key the boot accounting: a container
        # worker's registering pid is NOT the Popen pid (that's the
        # podman client), and pid reuse could misattribute anyway
        token = os.urandom(8).hex()
        env["RT_SPAWN_TOKEN"] = token
        argv = [sys.executable, "-m", "ray_tpu.core.worker_main"]
        if container is not None:
            env_hash, cspec = container
            env["RT_ENV_HASH"] = env_hash
            from ray_tpu.core.container import get_container_runtime

            import ray_tpu as _pkg

            pkg_root = os.path.dirname(
                os.path.dirname(os.path.abspath(_pkg.__file__))
            )
            mounts = sorted({
                os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"),
                self.session_dir, pkg_root, "/dev/shm",
            })
            try:
                # the WHOLE worker env crosses the boundary — a
                # container worker with default-config RT_* settings
                # would silently diverge from every host worker
                argv = get_container_runtime().synthesize(
                    cspec, argv,
                    {k: v for k, v in env.items() if v is not None},
                    mounts,
                )
            except Exception:
                # e.g. no podman/docker on this host: release the
                # pending-spawn slot or on-demand spawning wedges
                # forever for ALL tasks on this node
                self._pending_spawns -= 1
                logger.exception(
                    "container worker spawn failed for image %r",
                    cspec.get("image"),
                )
                raise
        proc = subprocess.Popen(
            argv,
            env=env,
            # worker spawn is deliberately synchronous on the daemon
            # loop (lease-grant ordering); the log-file open is a
            # bounded local create dwarfed by the fork+exec beside it,
            # and spawns are rare
            stdout=open(os.path.join(self.session_dir, "logs", f"worker-{time.time():.0f}-{os.urandom(2).hex()}.out"), "wb"),  # rtlint: disable=RT009
            stderr=subprocess.STDOUT,
        )
        # booting = spawned but not yet registered; token membership
        # (not pid presence in self.workers) decides who releases the
        # pending-spawn slot, so a registered worker's later death can
        # never double-release it
        self._booting_tokens.add(token)
        # the worker introduces itself via `register`; we just remember
        # the proc so we can reap/replace it
        asyncio.ensure_future(self._watch_proc(proc, token))

    async def _watch_proc(self, proc: subprocess.Popen, token: str):
        # a boot that HANGS (rather than crashes) would otherwise hold
        # its pending-spawn slot forever and wedge the pool at size 0 —
        # kill it past the deadline so the crash path releases the slot
        # and the next schedule pass can spawn a fresh worker
        boot_deadline = time.monotonic() + float(
            os.environ.get("RT_WORKER_BOOT_TIMEOUT_S", "120")
        )
        boot_killed = False
        while proc.poll() is None:
            if (not boot_killed and token in self._booting_tokens
                    and time.monotonic() > boot_deadline):
                logger.warning(
                    "worker pid %d still booting after deadline: killing",
                    proc.pid,
                )
                boot_killed = True  # once; an unkillable proc must not re-warn 5x/s
                try:
                    # containerized boots: the client SIGKILL below
                    # strands the container — kill it by name too
                    from ray_tpu.core.container import (
                        get_container_runtime,
                    )

                    get_container_runtime().kill_booting(token)
                except Exception as e:
                    logger.debug("kill_booting(%s) failed: %s", token, e)
                proc.kill()
            await asyncio.sleep(0.2)
        if token in self._booting_tokens:
            # died before registering: release the pending-spawn slot
            # so on-demand spawning doesn't deadlock on a boot-crashing
            # worker
            self._booting_tokens.discard(token)
            if self._pending_spawns > 0:
                self._pending_spawns -= 1
            logger.warning(
                "worker pid %d exited with %s before registering",
                proc.pid,
                proc.returncode,
            )
            return
        # registered at some point: normal death path (a racing
        # connection-close may have handled it already — then the pid
        # is no longer in self.workers and this is a no-op)
        for w in list(self.workers.values()):
            if w.pid == proc.pid:
                self._on_worker_dead(w, f"process exited with {proc.returncode}")
                return

    def on_connect(self, conn: rpc.Connection):
        conn.on_close = self._on_conn_close

    def _on_conn_close(self, conn: rpc.Connection):
        wid = self._conn_worker.pop(conn, None)
        if wid is None:
            return
        w = self.workers.get(wid)
        if w is not None and w.conn is conn:
            self._on_worker_dead(w, "connection lost")

    def _on_worker_dead(self, w: WorkerState, reason: str):
        if w.worker_id not in self.workers:
            return
        del self.workers[w.worker_id]
        logger.warning("worker %s died: %s", w.worker_id[:8], reason)
        if self._chip_pool is not None:
            self._chip_pool.release_worker(w.worker_id)
        if self.store is not None:
            self.store.reap_creator(w.pid)
        # fail in-flight tasks back to their owners
        for spec in w.in_flight.values():
            result = TaskResult(task_id=spec.task_id, status="worker_died")
            asyncio.ensure_future(self._route_to_owner(spec.owner, "task_result", result))
        # the tasks are dead with the worker: clear them BEFORE the
        # lease release, whose not-in-flight guard would otherwise skip
        # the resource refund forever (the worker is about to become
        # unreachable)
        w.in_flight = {}
        self._release_lease(w)
        if w.actor_id is not None:
            self._hosted_actors.pop(w.actor_id, None)
            if self.controller_conn:
                self.controller_conn.send(
                    "actor_worker_died",
                    {"actor_id": w.actor_id, "cause": reason,
                     "node_id": self.node_id},
                )
        if w.kind == "worker" and not self._draining:
            self._spawn_worker()
        self._schedule()

    _draining = False

    # ------------------------------------------------------------------
    # local registration
    # ------------------------------------------------------------------
    async def handle_register(self, payload, conn):
        w = WorkerState(
            worker_id=payload["worker_id"],
            pid=payload["pid"],
            conn=conn,
            kind=payload["kind"],
        )
        tok = payload.get("spawn_token")
        if tok and tok in self._booting_tokens:
            self._booting_tokens.discard(tok)
            if self._pending_spawns > 0:
                self._pending_spawns -= 1
        if payload.get("env_hash"):
            # spawned inside a container image: dedicated from birth
            w.env_hash = payload["env_hash"]
        w.socket_path = payload.get("socket_path")
        self.workers[w.worker_id] = w
        self._conn_worker[conn] = w.worker_id
        self._schedule()
        return {
            "node_id": self.node_id,
            "shm_name": self.shm_name,
            "controller_addr": list(self.controller_addr),
        }

    async def handle_ping(self, payload, conn):
        return "pong"

    # ------------------------------------------------------------------
    # scheduling (reference: local_task_manager.cc:122 dispatch loop)
    # ------------------------------------------------------------------
    async def handle_submit_task(self, spec: TaskSpec, conn):
        # the daemon's hop in a trace: tasks routed through the node
        # scheduler (spread/affinity/pg/labels, lease-infeasible
        # spillback) appear as an instant `sched:` span so the merged
        # timeline shows WHERE a task waited (driver vs daemon vs
        # worker).  Guarded by the spec carrying a context at all —
        # costs one attribute test when tracing is off.
        if spec.trace_ctx is not None:
            from ray_tpu.util import tracing as _tracing

            _tracing.record_instant(
                f"sched:{spec.name}", spec.trace_ctx, kind="INTERNAL",
                node=self.node_id[:8],
            )
        strat = spec.strategy
        if strat.kind == "placement_group" and strat.pg_id is not None:
            target = await self.controller_conn.call(
                "pg_node_for_bundle",
                {"pg_id": strat.pg_id, "bundle_index": strat.pg_bundle_index},
            )
            if target is not None and target != self.node_id:
                (await self._node_conn(target)).send("submit_task", spec)
                return
        elif strat.kind == "node_affinity" and strat.node_id:
            if strat.node_id != self.node_id:
                try:
                    (await self._node_conn(strat.node_id)).send("submit_task", spec)
                    return
                except Exception as e:
                    logger.debug("forward to node %s failed: %s",
                                 strat.node_id[:8], e)
                    if not strat.soft:
                        result = TaskResult(task_id=spec.task_id, status="worker_died")
                        await self._route_to_owner(spec.owner, "task_result", result)
                        return
        elif strat.kind == "node_labels":
            # a daemon with a local HARD match may host the task
            # outright (soft is only a preference); otherwise — local
            # hard miss, or a soft-only strategy that must see the
            # cluster-wide soft candidates — the controller picks via
            # filter_by_labels.  `label_routed` marks an already-routed
            # forward so the receiving daemon queues in one hop, while
            # the constraints stay attached for label-aware spillback.
            if strat.label_routed or (
                strat.label_hard
                and match_labels(strat.label_hard, self.node_labels)
            ):
                target = self.node_id
            else:
                target = await self.controller_conn.call(
                    "find_node_for",
                    {"resources": spec.resources.as_dict(), "exclude": [],
                     "label_hard": strat.label_hard,
                     "label_soft": strat.label_soft},
                )
            if target is None:
                from ray_tpu.core import serialization as ser

                result = TaskResult(
                    task_id=spec.task_id, status="infeasible",
                    error=ser.serialize_to_bytes(ValueError(
                        "no node matches NodeLabelSchedulingStrategy hard "
                        f"expressions {strat.label_hard}"),
                        tag=ser.TAG_ERROR),
                )
                await self._route_to_owner(spec.owner, "task_result", result)
                return
            if target != self.node_id:
                spec.strategy.label_routed = True
                (await self._node_conn(target)).send("submit_task", spec)
                return
        elif strat.kind == "spread":
            target = await self.controller_conn.call(
                "find_node_for",
                {"resources": spec.resources.as_dict(), "exclude": [],
                 "spread": True},
            )
            if target is not None and target != self.node_id:
                # the choice is made exactly once: the receiving daemon
                # must queue locally, not re-roll the round-robin (which
                # would ping-pong the task between nodes forever)
                spec.strategy = SchedulingStrategy()
                (await self._node_conn(target)).send("submit_task", spec)
                return
        self.task_queue.append(spec)
        self._schedule()

    def _schedule(self):
        """Dispatch as many queued tasks as possible.  Scans a bounded
        window past the head to avoid head-of-line blocking by an
        infeasible task (reference behavior: separate infeasible queue);
        each dispatch is O(window), keeping the 10k-tasks-queued case
        linear overall."""
        q = self.task_queue
        while q:
            dispatched = False
            for i in range(min(len(q), 64)):
                spec = q[i]
                w = self._find_worker_for(spec)
                if w is not None:
                    del q[i]
                    self._dispatch(w, spec)
                    dispatched = True
                    break
            if not dispatched:
                asyncio.ensure_future(self._maybe_spill(q[0]))
                break
        # spawn extra workers if queue is deep and the pool is small.
        # Workers still BOOTING (spawned, not yet registered) count
        # against the pool — without that, every schedule pass during a
        # slow boot (jax import takes seconds; worse when the core is
        # contended) spawns another worker, and each new boot slows the
        # others further: a spawn storm (reference: starting-worker
        # accounting in `worker_pool.cc` MaybeStartNewWorker)
        head = self._spec_container(q[0]) if q else None
        if head is not None and not _fits(
            q[0].resources.as_dict(), self.available
        ):
            # a saturated node must not boot dedicated container
            # workers it cannot lease — they can never serve plain
            # tasks and each boot costs seconds and memory
            head = None
        # blocked workers (parked mid-task on an unavailable object)
        # don't count toward the pool: when every slot holds a blocked
        # consumer, the queued producer tasks need a fresh worker or
        # the node deadlocks on its own lineage reconstruction
        unblocked = sum(
            1 for ws in self.workers.values() if not ws.blocked
        )
        if q and (
            unblocked + self._pending_spawns < self.num_workers
            or (head is not None and self._pending_spawns == 0
                and len(self.workers) <= self.num_workers * 2)
        ):
            # container demands need a DEDICATED image-spawned worker:
            # the pre-spawned host pool can never serve them, so the
            # pool-full gate alone would starve queued container tasks
            try:
                self._spawn_worker(
                    container=((q[0].env_hash, head) if head else None)
                )
            except Exception as e:
                logger.debug("worker spawn for env failed: %s", e)
                # the env cannot be materialized on this host (no
                # podman/docker, bad image): fail the queued tasks of
                # that env with the cause — retrying every tick would
                # hang them forever while spamming the log (the lease
                # path returns env_error for the same contract)
                from ray_tpu.core import serialization as ser

                bad_env = q[0].env_hash
                doomed = [s for s in q
                          if s.env_hash == bad_env
                          and self._spec_container(s) is not None]
                for s in doomed:
                    q.remove(s)
                    result = TaskResult(
                        task_id=s.task_id, status="error",
                        error=ser.serialize_to_bytes(RuntimeError(
                            "runtime_env setup failed: container "
                            f"worker spawn failed: {e}"),
                            tag=ser.TAG_ERROR),
                    )
                    asyncio.ensure_future(self._route_to_owner(
                        s.owner, "task_result", result
                    ))

    @staticmethod
    def _spec_container(spec) -> Optional[Dict]:
        """Container section of a spec's runtime env (daemon-routed
        tasks carry the full env in the spec).  env_hash-gated: specs
        with no runtime env (the overwhelmingly common case — this
        runs inside the scheduling scan) exit without touching the
        env dict."""
        if getattr(spec, "env_hash", None) is None:
            return None
        try:
            from ray_tpu.core.container import container_section

            return container_section(getattr(spec, "runtime_env", None))
        except Exception as e:
            logger.debug("resolving container section failed: %s", e)
            return None

    def _find_worker_for(self, spec: TaskSpec) -> Optional[WorkerState]:
        demand = spec.resources.as_dict()
        # 1) pipeline onto a worker already leased with identical
        # demand AND runtime env
        for w in self.workers.values():
            if (
                w.kind == "worker"
                and w.actor_id is None
                and w.leased_to is None
                and not w.blocked  # parked mid-get: don't stack work
                and w.lease is not None
                and w.lease == demand
                and w.env_hash == spec.env_hash
                and len(w.in_flight) < _PIPELINE_DEPTH
            ):
                return w
        # 2) idle worker + available resources (chip/env-pinning aware)
        if _fits(demand, self.available):
            tpu_n = self._tpu_chips_needed(demand)
            w = self._pick_idle_worker(
                tpu_n, require_no_lease=True, env_hash=spec.env_hash,
                require_exact_env=self._spec_container(spec) is not None,
            )
            if w is None:
                # idle workers may be pinned to the wrong chip count or
                # env; retire one so the queued task can't starve
                self._reclaim_idle_pinned(tpu_n, spec.env_hash)
                return None
            if tpu_n and not self._assign_chips(w, tpu_n):
                self._reclaim_idle_pinned(tpu_n, spec.env_hash)
                return None
            if spec.env_hash is not None:
                w.env_hash = spec.env_hash
            return w
        return None

    def _dispatch(self, w: WorkerState, spec: TaskSpec):
        demand = spec.resources.as_dict()
        if w.lease is None:
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            w.lease = demand
        if w.busy_since is None:
            w.busy_since = time.time()
        w.in_flight[spec.task_id.binary()] = spec
        w.conn.send("execute_task", spec)

    def _release_lease(self, w: WorkerState):
        if w.lease is not None and not w.in_flight:
            if not w.blocked:
                # a blocked worker's lease resources were already
                # returned at block time; re-adding them here would
                # mint resources out of thin air
                for k, v in w.lease.items():
                    self.available[k] = self.available.get(k, 0.0) + v
            w.blocked = False
            w.lease = None
        if w.idle:
            w.busy_since = None

    async def _memory_monitor_loop(self):
        """Poll node memory; kill a busy task worker when over the
        threshold (reference: `memory_monitor.h:52` driving
        `worker_killing_policy.h:34` in the raylet).  The killed
        worker's tasks fail back to their owners as worker_died —
        retriable work retries (possibly elsewhere), and the node
        survives instead of the kernel OOM killer taking the daemon."""
        from ray_tpu.core.memory_monitor import MemoryMonitor, pick_oom_victim

        monitor = MemoryMonitor(self.cfg.memory_usage_threshold)
        period = self.cfg.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                if not monitor.is_usage_above_threshold():
                    continue
                victim = pick_oom_victim(
                    list(self.workers.values()),
                    self.cfg.worker_killing_policy,
                )
                if victim is None:
                    continue
                used, total = monitor.get_memory_usage()
                logger.warning(
                    "memory usage %.1f%% above threshold %.1f%%: killing "
                    "worker %s (policy=%s) to free memory",
                    100 * used / max(total, 1),
                    100 * self.cfg.memory_usage_threshold,
                    victim.worker_id[:8],
                    self.cfg.worker_killing_policy,
                )
                victim.oom_killed_at = time.time()
                monitor.reset()  # one kill per sustained breach
                try:
                    os.kill(victim.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            except Exception:
                logger.exception("memory monitor pass failed")

    async def _retry_queue_loop(self):
        """Periodic housekeeping: re-attempt queued-but-infeasible tasks
        (cluster membership changes arrive asynchronously and nothing
        else re-triggers the scan) and report load to the controller
        (the RaySyncer-style resource gossip the autoscaler's idle
        detection reads — reference: `ray_syncer.h:88`)."""
        while True:
            await asyncio.sleep(1.0)
            if self.task_queue:
                self._schedule()
            try:
                if self.store.used > self.SPILL_HIGH * self.store.capacity:
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._maybe_spill_objects
                    )
            except Exception:
                logger.exception("object spill pass failed")
            try:
                used = {
                    k: self.total_resources.get(k, 0.0) - v
                    for k, v in self.available.items()
                    if self.total_resources.get(k, 0.0) - v > 0
                }
                busy = bool(used) or bool(self.task_queue) or any(
                    w.in_flight or w.actor_id is not None
                    for w in self.workers.values()
                )
                # per-node reporter (reference: `dashboard/agent.py:25` +
                # reporter_agent.py): worker inventory + host stats ride
                # the load report, so the state API's list_workers reads
                # ONE controller snapshot instead of fanning out an RPC
                # per node per call
                from ray_tpu.core.memory_monitor import _system_memory

                mem_used, mem_total = _system_memory()
                try:
                    load1 = os.getloadavg()[0]
                except OSError:
                    load1 = 0.0
                report = {
                    "used": used, "busy": busy,
                    "queued": len(self.task_queue),
                    "workers": self._worker_inventory(),
                    "host": {
                        "load1": load1,
                        "mem_used": mem_used,
                        "mem_total": mem_total,
                    },
                }
                self.controller_conn.send(
                    "report_node_load", self._load_sync_payload(report)
                )
            except Exception as e:
                logger.debug("load report dropped: %s", e)

    # RaySyncer-style delta sync (reference: `ray_syncer.h:88`): send
    # only fields that changed since the last report, a bare-version
    # heartbeat when nothing did, and a full snapshot every
    # LOAD_FULL_EVERY ticks so a restarted/diverged controller
    # resynchronizes without a handshake.
    LOAD_FULL_EVERY = 10

    def _load_sync_payload(self, report: Dict[str, Any]) -> Dict[str, Any]:
        tick = self._load_tick = getattr(self, "_load_tick", 0) + 1
        last = getattr(self, "_last_load_report", None)
        v = getattr(self, "_load_v", 0)
        if last is None or tick % self.LOAD_FULL_EVERY == 0:
            self._load_v = v = v + 1
            payload = {"node_id": self.node_id, "v": v, "full": report}
        else:
            delta = {
                k: val for k, val in report.items() if last.get(k) != val
            }
            if delta:
                self._load_v = v = v + 1
                payload = {"node_id": self.node_id, "v": v,
                           "base": v - 1, "delta": delta}
            else:
                payload = {"node_id": self.node_id, "v": v}  # heartbeat
        self._last_load_report = report
        return payload

    # ------------------------------------------------------------------
    # object spilling (reference: LocalObjectManager, SpillObjects
    # `local_object_manager.h:110`): above the high watermark, persist
    # LRU sealed objects to disk and delete them from shm; restore on
    # demand.  Distinct from eviction: spilled primaries survive without
    # lineage recomputation.
    # ------------------------------------------------------------------
    SPILL_HIGH = 0.80
    SPILL_LOW = 0.60

    def _maybe_spill_objects(self, force: bool = False,
                             drain: bool = False):
        """Runs on an executor thread (sync file IO); serialized by
        _spill_lock against concurrent urgent-spill requests.

        All file I/O rides the `core/diskio.py` chokepoint (atomic
        tmp+rename, DiskChaos-injectable).  Failure discipline: a
        write that fails UN-ELECTS its object — the bytes were never
        deleted from shm and the atomic write left no partial file —
        so a flaky disk degrades spill throughput, never data.  Real
        or injected ENOSPC latches `_spill_disk_full` and ends the
        pass; the low-disk watermark stops *electing* spills before
        the disk is actually full."""
        import errno as _errno

        with self._spill_lock:
            cap = self.store.capacity
            if cap <= 0:
                return 0
            if not force and self.store.used <= self.SPILL_HIGH * cap:
                return 0
            # a DRAINING forced spill evicts EVERY unpinned object: the
            # blocked create needs a contiguous region, and free bytes
            # above the LOW watermark may be too fragmented to satisfy
            # it — stopping at the watermark can wedge an
            # allocator-fragmented store forever at 60% used.  Callers
            # escalate to drain only after watermark-target passes
            # failed, so brief pressure doesn't dump the working set.
            target = 0 if (force and drain) else int(self.SPILL_LOW * cap)
            os.makedirs(self._spill_dir, exist_ok=True)
            if (_diskio.free_bytes(self._spill_dir)
                    < self.cfg.spill_disk_min_free_bytes):
                if not self._spill_disk_full:
                    logger.warning(
                        "spill disk below the free-space watermark "
                        "(%d MB): not electing new spills",
                        self.cfg.spill_disk_min_free_bytes >> 20,
                    )
                self._spill_disk_full = True
                _fault_metric("rt_spill_disk_full_total")
                return 0
            self._spill_disk_full = False
            spilled = 0
            spilled_bytes = 0
            for id_bytes in self.store.spill_candidates(64):
                if self.store.used <= target:
                    break
                try:
                    view = self.store.get(id_bytes, timeout_ms=0)
                except Exception as e:
                    logger.debug("spill candidate %s not gettable: %s",
                                 id_bytes.hex()[:12], e)
                    continue
                try:
                    data = bytes(view)
                finally:
                    del view
                    self.store.release(id_bytes)
                crc = (_integrity.checksum(data)
                       if self.cfg.object_integrity else None)
                path = os.path.join(self._spill_dir, id_bytes.hex() + ".bin")
                try:
                    _diskio.write_file(path, data)
                except OSError as e:
                    # un-elected: still resident in shm, no partial file
                    if e.errno == _errno.ENOSPC:
                        self._spill_disk_full = True
                        _fault_metric("rt_spill_disk_full_total")
                        logger.warning("spill hit ENOSPC; disk full — "
                                       "ending the pass")
                        break
                    _fault_metric("rt_spill_errors_total",
                                  tags={"op": "spill"})
                    logger.warning("spill write of %s failed: %s",
                                   id_bytes.hex()[:12], e)
                    continue
                if crc is not None:
                    try:  # diagnostics sidecar; the in-memory manifest
                        # entry is authoritative for verification
                        _diskio.write_file(path + ".meta", json.dumps({
                            "size": len(data), "crc": crc,
                            "algo": _integrity.ALGO,
                        }).encode())
                    except OSError as e:
                        logger.debug("spill meta for %s not written: %s",
                                     id_bytes.hex()[:12], e)
                if not self.store.delete(id_bytes):
                    # pinned between candidate scan and delete: the
                    # bytes stay resident, the file is garbage
                    self._remove_spill_files(path)
                    continue
                self._spilled[id_bytes] = _SpillEntry(
                    path, len(data), crc, _integrity.ALGO
                )
                spilled += 1
                spilled_bytes += len(data)
            if spilled:
                _md.inc("rt_object_spill_bytes_total", float(spilled_bytes))
                logger.info("spilled %d objects to disk (store %.0f%% full)",
                            spilled, 100 * self.store.used / cap)
            return spilled

    @staticmethod
    def _remove_spill_files(path: str):
        for p in (path, path + ".meta"):
            try:
                os.remove(p)
            except OSError:
                pass

    def _quarantine_spilled(self, id_bytes: bytes, ent: _SpillEntry,
                            reason: str):
        """A spilled file failed verification: move it (and its
        sidecar) aside for post-mortem instead of deleting the
        evidence, count the event, and drop the manifest entry so the
        caller falls through to lineage reconstruction."""
        os.makedirs(self._quarantine_dir, exist_ok=True)
        for p in (ent.path, ent.path + ".meta"):
            try:
                os.replace(p, os.path.join(self._quarantine_dir,
                                           os.path.basename(p)))
            except OSError:
                pass
        self._spilled.pop(id_bytes, None)
        _fault_metric("rt_object_integrity_errors_total",
                      tags={"path": "restore"})
        _fault_metric("rt_object_quarantined_total")
        logger.error(
            "spilled object %s failed verification (%s): quarantined to "
            "%s; the object is treated as lost and re-derives via "
            "lineage where retained",
            id_bytes.hex()[:12], reason, self._quarantine_dir,
        )

    def _restore_spilled(self, id_bytes: bytes) -> bool:
        import errno as _errno

        from ray_tpu.core.retry import backoff_delay_s as _backoff

        with self._spill_lock:
            ent = self._spilled.get(id_bytes)
            if ent is None:
                return False
            # EIO is often transient (a device resetting): retry the
            # read through the jittered backoff schedule before
            # charging the caller a full lineage re-derivation
            data = None
            attempts = max(1, self.cfg.disk_io_retries)
            for attempt in range(attempts):
                try:
                    data = _diskio.read_file(ent.path)
                    break
                except OSError as e:
                    _fault_metric("rt_spill_errors_total",
                                  tags={"op": "restore"})
                    if (attempt + 1 >= attempts
                            or e.errno not in (_errno.EIO, _errno.EAGAIN)):
                        logger.warning(
                            "restore read of %s failed after %d "
                            "attempt(s): %s", id_bytes.hex()[:12],
                            attempt + 1, e,
                        )
                        self._spilled.pop(id_bytes, None)
                        self._remove_spill_files(ent.path)
                        return False
                    time.sleep(_backoff(attempt, base_s=0.02, cap_s=0.25))
            if len(data) != ent.size:
                self._quarantine_spilled(
                    id_bytes, ent,
                    f"size {len(data)} != recorded {ent.size}",
                )
                return False
            if (self.cfg.object_integrity
                    and not _integrity.verify(data, ent.crc, ent.algo)):
                self._quarantine_spilled(
                    id_bytes, ent,
                    f"checksum mismatch ({ent.algo} "
                    f"{_integrity.checksum(data):#x} != recorded "
                    f"{(ent.crc or 0):#x})",
                )
                return False
            if not self.store.contains(id_bytes):
                if not self._restore_into_store(id_bytes, data):
                    return False
            self._spilled.pop(id_bytes, None)
            self._remove_spill_files(ent.path)
            _md.inc("rt_object_restore_bytes_total", float(len(data)))
            return True

    def _restore_into_store(self, id_bytes: bytes, data: bytes) -> bool:
        """Create+copy+seal with the partial allocation released on ANY
        failure — an unsealed create would otherwise hold store bytes
        until a creator-death reap that never comes (the daemon is the
        creator and it is alive)."""
        for attempt in (0, 1):
            try:
                dest = self.store.create(id_bytes, len(data),
                                         allow_evict=False)
            except ObjectExistsError:
                return True  # raced another restore path
            except Exception as e:
                if attempt:
                    # still pressured; caller retries after the
                    # next spill pass frees room
                    logger.debug("restore of %s blocked: %s",
                                 id_bytes.hex()[:12], e)
                    return False
                # make room by force-spilling OTHER unpinned
                # objects (full drain: the restore needs a
                # contiguous region NOW), then retry once — a
                # restore that fails here costs the borrower a
                # full lineage re-derivation (_spill_lock is
                # reentrant)
                self._maybe_spill_objects(force=True, drain=True)
                continue
            try:
                dest[:] = data
                self.store.seal(id_bytes)
                return True
            except Exception:
                logger.exception("restore copy/seal of %s failed; "
                                 "releasing the partial allocation",
                                 id_bytes.hex()[:12])
                try:
                    del dest
                    # abort, not delete: the unsealed create holds its
                    # creator pin, which a bare delete refuses to free
                    self.store.abort(id_bytes)
                except Exception as de:
                    logger.debug("partial-restore abort failed: %s", de)
                return False
        return False

    # ------------------------------------------------------------------
    # observability plane: /metrics HTTP + batched obs frames
    # ------------------------------------------------------------------
    async def _start_metrics_http(self, bind: str):
        """Prometheus text exposition for THIS daemon's registry
        (reference: the per-node metrics agent's scrape endpoint).  A
        positive cfg port is taken literally only by the head daemon —
        worker daemons on the same host bind ephemeral ports — and a
        bind failure degrades to ephemeral instead of killing boot."""
        from ray_tpu.util import httpd

        want = self.cfg.metrics_http_port
        port = want if (want > 0 and self.is_head) else 0
        try:
            self._metrics_server, self.metrics_http_port = (
                await httpd.serve_http(bind, port, self._metrics_dispatch)
            )
        except OSError as e:
            if port == 0:
                logger.warning("metrics HTTP listener failed: %s", e)
                return
            logger.warning(
                "metrics port %d unavailable (%s); using ephemeral",
                port, e,
            )
            self._metrics_server, self.metrics_http_port = (
                await httpd.serve_http(bind, 0, self._metrics_dispatch)
            )
        logger.info("noded %s /metrics on %s:%d",
                    self.node_name, bind, self.metrics_http_port)

    async def _metrics_dispatch(self, req):
        from ray_tpu.metrics.registry import export_text

        if req.path.rstrip("/") == "/metrics":
            self._refresh_store_gauges()
            return 200, "text/plain; version=0.0.4", export_text().encode()
        return 404, "text/plain", b"not found"

    def _refresh_store_gauges(self):
        """Object-plane level gauges, recomputed at scrape/report time
        (no hot-path cost; bypasses the metrics_enabled gate the same
        way the dashboard's builtin gauges do)."""
        from ray_tpu.metrics import metric_defs as _mdefs

        if self.store is None:
            return
        _mdefs.metric("rt_object_store_used_bytes").set(
            float(self.store.used))
        _mdefs.metric("rt_object_store_capacity_bytes").set(
            float(self.store.capacity))
        _mdefs.metric("rt_object_store_objects").set(
            float(self.store.count))
        _mdefs.metric("rt_object_spilled_objects").set(
            float(len(self._spilled)))

    async def _obs_report_loop(self):
        """One batched `report_obs` frame per interval on the existing
        controller connection: this daemon's metrics snapshot plus any
        scheduling spans recorded since the last flush.  Mirrors the
        runtime-side flush loop (`core/runtime.py`); never a
        per-sample RPC."""
        from ray_tpu.metrics import exporter as _mexp

        period_s = max(0.5, self.cfg.metrics_report_interval_ms / 1000.0)
        while True:
            await asyncio.sleep(period_s)
            conn = self.controller_conn
            if conn is None or conn.closed:
                # reconnect loop restores it; spans stay in the bounded
                # export queue meanwhile (overflow there is COUNTED —
                # draining before this check would discard them silently)
                continue
            payload = _mexp.build_obs_payload(
                self.node_id, "noded", os.getpid(),
                refresh=self._refresh_store_gauges,
            )
            if payload is None:
                continue
            try:
                conn.send("report_obs", payload)
            except Exception as e:
                logger.debug("daemon obs frame dropped: %s", e)

    async def handle_cancel_task(self, payload, conn):
        """Drop a still-queued task (reference:
        CancelTask on the raylet for unleased tasks)."""
        task_id = payload["task_id"]
        for i, spec in enumerate(self.task_queue):
            if spec.task_id.binary() == task_id:
                del self.task_queue[i]
                from ray_tpu.core import serialization as ser
                from ray_tpu import exceptions as exc

                envelope = ser.serialize_to_bytes(
                    exc.TaskCancelledError(task_id=spec.task_id),
                    tag=ser.TAG_ERROR,
                )
                await self._route_to_owner(
                    spec.owner, "task_result",
                    TaskResult(task_id=spec.task_id, status="error",
                               error=envelope),
                )
                return {"cancelled": True}
        # dispatched already: forward to the worker running it (its
        # runtime delivers the mid-execution interrupt), then try the
        # other daemons once — daemon-routed tasks may run anywhere
        for w in list(self.workers.values()):
            if task_id in w.in_flight and w.conn and not w.conn.closed:
                try:
                    return await w.conn.call(
                        "cancel_task", {"task_id": task_id}, timeout=10
                    )
                except Exception as e:
                    logger.debug("cancel_task relay failed: %s", e)
                    return {"cancelled": False}
        if not payload.get("forwarded"):
            reply = await self._fanout_once(
                "cancel_task", {"task_id": task_id},
                done=lambda r: r and r.get("cancelled"),
            )
            if reply:
                return reply
        return {"cancelled": False}

    async def handle_restore_object(self, payload, conn):
        ok = await asyncio.get_running_loop().run_in_executor(
            None, self._restore_spilled, payload["id"]
        )
        return {"ok": ok}

    async def handle_spill_now(self, payload, conn):
        """Urgent spill on create-backpressure (the reference's create
        queue triggering spilling, `create_request_queue.h`).  The
        caller escalates `drain` after watermark-target passes failed
        to unblock its create (fragmentation)."""
        drain = bool(payload and payload.get("drain"))
        try:
            n = await asyncio.get_running_loop().run_in_executor(
                None, self._maybe_spill_objects, True, drain
            )
        except Exception:
            logger.exception("urgent spill failed")
            n = 0
        # disk_full tells the blocked producer to clamp with a typed
        # BackPressureError instead of spinning out its create deadline
        # against a disk that cannot absorb another spill
        return {"spilled": n, "disk_full": self._spill_disk_full}

    async def _maybe_spill(self, spec: TaskSpec):
        """Spillback: if this node can never or not-soon run the task,
        hand it to another node (reference: cluster_task_manager.cc:44).
        Hard label constraints ride along — spillback must never move a
        task onto a node its NodeLabelSchedulingStrategy excludes."""
        demand = spec.resources.as_dict()
        if _fits(demand, self.total_resources):
            return  # feasible here, just busy: keep queued
        if self.controller_conn is None:
            return
        query = {"resources": demand, "exclude": [self.node_id]}
        if spec.strategy.kind == "node_labels":
            query["label_hard"] = spec.strategy.label_hard
            query["label_soft"] = spec.strategy.label_soft
        target = await self.controller_conn.call("find_node_for", query)
        if target is None:
            # unschedulable cluster-wide: feed the autoscaler's demand
            # ledger (reference: pending demand in LoadMetrics driving
            # resource_demand_scheduler.py)
            try:
                self.controller_conn.send(
                    "report_pending_demand", {"resources": demand}
                )
            except Exception as e:
                logger.debug("pending-demand report dropped: %s", e)
            return  # stays queued
        for i, s in enumerate(self.task_queue):
            if s is spec:
                del self.task_queue[i]
                break
        else:
            return  # already dispatched elsewhere
        conn = await self._node_conn(target)
        conn.send("submit_task", spec)

    # ------------------------------------------------------------------
    # worker leasing: direct-push protocol (reference two-level
    # scheduling — leases granted here, tasks pushed caller->worker)
    # ------------------------------------------------------------------
    # -- TPU chip isolation (see core/accelerators.py) -----------------
    def _tpu_chips_needed(self, demand: Dict[str, float]) -> int:
        t = float(demand.get("TPU", 0.0))
        return int(t) if t >= 1 and t.is_integer() else 0

    def _assign_chips(self, w: WorkerState, n: int) -> bool:
        """Pin `n` chips to worker `w` (no-op match if already pinned to
        exactly n) and push the isolation env over its conn.  Safe for
        the daemon-dispatch path: the env rides the same ordered stream
        as the execute_task push that follows.  The direct-push lease
        path must use `_assign_chips_acked` instead — there the task
        arrives on a different conn (caller -> worker) and nothing else
        orders the two streams."""
        if self._chip_pool is None:
            return True
        chips = self._chip_pool.assign(w.worker_id, n)
        if chips is None:
            return False
        env = accelerators.chip_isolation_env(
            list(chips), self._chip_pool.num_chips
        )
        try:
            w.conn.send("set_accel_env", env)
        except Exception as e:
            logger.debug("set_accel_env send to %s failed: %s",
                         w.worker_id[:8], e)
            return False
        return True

    async def _assign_chips_acked(self, w: WorkerState, n: int) -> bool:
        """Like `_assign_chips` but waits for the worker to acknowledge
        the env before returning, so a lease reply cannot race the
        caller's first direct task push past the isolation setup."""
        if self._chip_pool is None:
            return True
        chips = self._chip_pool.assign(w.worker_id, n)
        if chips is None:
            return False
        env = accelerators.chip_isolation_env(
            list(chips), self._chip_pool.num_chips
        )
        try:
            await w.conn.call("set_accel_env", env, timeout=10)
        except Exception as e:
            logger.debug("set_accel_env call to %s failed: %s",
                         w.worker_id[:8], e)
            return False
        return True

    def _pick_idle_worker(
        self, tpu_n: int, require_no_lease: bool = False,
        env_hash: Optional[str] = None, require_exact_env: bool = False,
    ) -> Optional[WorkerState]:
        """Idle-worker choice, chip- and env-pinning aware: an n-chip
        demand prefers a worker already pinned to n chips (its runtime
        is initialized against them), then an unpinned one.  Env
        matching is STRICT: a tainted worker serves only its own env
        hash, a clean demand only clean workers — a demand with an env
        may also take a clean worker (which becomes dedicated)."""
        pinned_match = unpinned = any_idle = None
        for w in self.workers.values():
            if not (w.kind == "worker" and w.idle and w.conn and w.socket_path):
                continue
            if require_no_lease and w.lease is not None:
                continue
            if w.env_hash is not None and w.env_hash != env_hash:
                continue  # tainted with a different env: never reuse
            if require_exact_env and w.env_hash != env_hash:
                # container envs: a plain worker cannot enter an image
                # from inside a running process — only a worker spawned
                # IN the image (pre-dedicated) may serve this demand
                continue
            # env_ready: this worker already applied the demanded env
            # (a clean worker serving an env demand is acceptable but a
            # same-env worker is better); for clean demands both are
            # equal (only clean workers reach here)
            env_ready = w.env_hash == env_hash
            held = (
                self._chip_pool.pinned(w.worker_id)
                if self._chip_pool is not None
                else None
            )
            if held is None and env_ready:
                unpinned = unpinned or w
            elif tpu_n and held is not None and len(held) == tpu_n:
                pinned_match = pinned_match or w
            else:
                # chip-pinned worker for a CPU demand, or a clean
                # worker for an env demand: usable fallback
                any_idle = any_idle or w
        if tpu_n:
            return pinned_match or unpinned or any_idle
        return unpinned or any_idle

    def _reclaim_idle_pinned(self, tpu_n: int,
                             env_hash: Optional[str] = None) -> None:
        """Pinning fragmentation: the demand can't be served because
        idle workers are pinned to the wrong chip shape or dedicated to
        a different runtime env.  Retire one such worker (its death
        releases chips, frees a pool slot, and respawns clean)."""
        chips_short = (
            tpu_n and self._chip_pool is not None
            and self._chip_pool.free_count < tpu_n
        )
        for w in self.workers.values():
            if not (w.kind == "worker" and w.idle):
                continue
            held = (
                self._chip_pool.pinned(w.worker_id)
                if self._chip_pool is not None else None
            )
            chip_mismatch = chips_short and held and len(held) != tpu_n
            env_mismatch = (
                w.env_hash is not None and w.env_hash != env_hash
            )
            if chip_mismatch or env_mismatch:
                try:
                    os.kill(w.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError) as e:
                    logger.debug("killing mismatched worker %d: %s",
                                 w.pid, e)
                return

    async def handle_request_lease(self, payload, conn):
        """Grant leased worker(s) to a caller (reference:
        `HandleRequestWorkerLease` node_manager.cc:1797).

        With `count` in the payload (the batched negotiation of the
        sharded owner plane) the reply is `{"grants": [(worker_id,
        socket_path), ...]}` — up to `count` grants from ONE daemon
        pass, so a submission burst amortizes lease RPCs instead of
        paying one round trip per worker.  Without `count` the legacy
        single-grant shapes are preserved: (worker_id, socket_path),
        None, {"infeasible": True}, or {"env_error": ...}."""
        demand = payload["resources"]
        holder = self._conn_worker.get(conn, "remote")
        batched = "count" in payload
        want = max(1, int(payload.get("count", 1)))
        if not _fits(demand, self.total_resources):
            # never feasible on this node: tell the caller to reroute
            # through the queue path, which spills to a feasible node
            # (reference: spillback in cluster_task_manager.cc:44)
            return {"infeasible": True}
        env_hash = payload.get("env_hash")
        container = payload.get("container")
        grants = []
        err = None
        for _ in range(want):
            grant = await self._grant_one_lease(
                demand, env_hash, container, holder
            )
            if isinstance(grant, dict):  # env_error from a spawn attempt
                err = grant
                break
            if grant is None:
                break
            grants.append(grant)
        if batched:
            if not grants and err is not None:
                return err
            return {"grants": grants}
        if grants:
            return grants[0]
        return err  # None or {"env_error": ...}

    async def _grant_one_lease(self, demand, env_hash, container, holder):
        """One grant attempt: (worker_id, socket_path) on success, None
        when nothing is available right now (spawn-on-demand may have
        been kicked), or {"env_error": ...} when the env can never
        materialize here."""
        if not _fits(demand, self.available):
            return None
        tpu_n = self._tpu_chips_needed(demand)
        w = self._pick_idle_worker(
            tpu_n, env_hash=env_hash,
            require_exact_env=container is not None,
        )
        if w is not None:
            # reserve BEFORE any await: a concurrent lease request must
            # see these resources as taken or the node oversubscribes
            # (same reserve-then-wait shape as handle_host_actor)
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            ok = True
            if tpu_n:
                ok = await self._assign_chips_acked(w, tpu_n)
            if ok and not w.idle:
                # the env ack yielded the loop: somebody else took this
                # worker meanwhile
                ok = False
            if not ok:
                for k, v in demand.items():
                    self.available[k] = self.available.get(k, 0.0) + v
                w = None
        if w is not None:
            if env_hash is not None:
                # dedicate only on a SUCCESSFUL grant: a worker must
                # never be marked with an env it never applied
                w.env_hash = env_hash
            w.lease = dict(demand)
            w.leased_to = holder
            w.busy_since = time.time()
            return (w.worker_id, w.socket_path)
        self._reclaim_idle_pinned(tpu_n, env_hash)
        # blocked workers don't count toward the spawn cap (reference:
        # blocked workers are excluded from the pool-size accounting,
        # which is how Ray runs more workers than cores while gets are
        # parked): when every slot holds a consumer blocked on an
        # object only a queued producer can re-derive, the producer
        # needs a fresh worker or the node deadlocks
        unblocked = sum(
            1 for ws in self.workers.values() if not ws.blocked
        )
        if self._pending_spawns == 0 and unblocked <= self.num_workers * 2:
            try:
                self._spawn_worker(
                    container=((env_hash, container) if container else None)
                )
            except Exception as e:
                logger.debug("worker spawn failed: %s", e)
                # surface spawn failures (no podman on host, bad image)
                # to the caller: the driver fails the queued tasks with
                # a runtime-env error instead of retrying forever
                return {"env_error": f"container worker spawn failed: {e}"}
        return None

    # ------------------------------------------------------------------
    # blocked-worker CPU release (reference: raylet HandleTaskBlocked /
    # HandleTaskUnblocked): a worker whose in-task get() parks on an
    # unavailable object hands its lease resources back so the work
    # that PRODUCES the object (spill restores are daemon-side, but
    # lineage re-derivation needs a worker slot) can be scheduled —
    # possibly on a freshly spawned worker when the whole pool is
    # blocked.  Unblock re-charges the resources; the node may run
    # transiently oversubscribed, exactly like the reference.
    # ------------------------------------------------------------------
    async def handle_worker_blocked(self, payload, conn):
        wid = self._conn_worker.get(conn)
        w = self.workers.get(wid) if wid else None
        if w is None or w.blocked or w.lease is None:
            return {"ok": False}
        w.blocked = True
        for k, v in w.lease.items():
            self.available[k] = self.available.get(k, 0.0) + v
        self._schedule()
        return {"ok": True}

    async def handle_worker_unblocked(self, payload, conn):
        wid = self._conn_worker.get(conn)
        w = self.workers.get(wid) if wid else None
        if w is None or not w.blocked:
            return {"ok": False}
        w.blocked = False
        if w.lease is not None:
            for k, v in w.lease.items():
                self.available[k] = self.available.get(k, 0.0) - v
        return {"ok": True}

    async def handle_return_lease(self, payload, conn):
        w = self.workers.get(payload["worker_id"])
        if w is None or w.leased_to is None:
            return {"ok": False}
        w.leased_to = None
        w.in_flight.clear()
        self._release_lease(w)
        self._schedule()
        return {"ok": True}

    async def handle_resolve_worker_socket(self, payload, conn):
        node_id = payload.get("node_id", self.node_id)
        if node_id != self.node_id:
            try:
                c = await self._node_conn(node_id)
                return await c.call(
                    "resolve_worker_socket",
                    {"node_id": node_id, "worker_id": payload["worker_id"]},
                )
            except Exception as e:
                logger.debug("resolve_worker_socket relay failed: %s", e)
                return None
        w = self.workers.get(payload["worker_id"])
        return w.socket_path if w else None

    # ------------------------------------------------------------------
    # task completion (noded-dispatched tasks only; direct pushes reply
    # straight to the owner)
    # ------------------------------------------------------------------
    async def handle_task_done(self, payload, conn):
        result: TaskResult = payload["result"]
        owner = payload["owner"]
        wid = self._conn_worker.get(conn)
        w = self.workers.get(wid) if wid else None
        if w is not None:
            w.in_flight.pop(result.task_id.binary(), None)
            self._release_lease(w)
        await self._route_to_owner(owner, "task_result", result)
        self._schedule()

    # worker replies arrive as task_result on its registration conn for
    # tasks this daemon dispatched (spillback / relayed actor tasks)
    handle_task_result = handle_task_done

    async def handle_task_result_batch(self, payload, conn):
        """Coalesced completion frame from a worker (daemon-dispatched
        tasks reply on the registration conn): per-result lease
        bookkeeping, then ONE routed frame to the owner for the whole
        batch — the daemon's relay cost stays O(#frames)."""
        results = list(payload.results)
        owner = tuple(payload.owner)
        wid = self._conn_worker.get(conn)
        w = self.workers.get(wid) if wid else None
        if w is not None:
            for r in results:
                w.in_flight.pop(r.task_id.binary(), None)
            self._release_lease(w)
        await self._route_to_owner(owner, "task_result_batch", payload)
        self._schedule()

    async def handle_task_stream(self, payload, conn):
        """Relay one streaming-generator item to the task's owner (used
        when the executor's direct conn to the owner is gone, and for
        daemon-dispatched tasks whose items arrive on the worker's
        registration conn)."""
        await self._route_to_owner(payload["owner"], "stream_item", payload)

    handle_stream_item = handle_task_stream

    async def handle_route_node(self, payload, conn):
        """Forward a daemon method call to another node's daemon (the
        state API's cross-node fan-out rides this)."""
        node_id = payload["node_id"]
        method = payload["method"]
        if node_id == self.node_id:
            handler = getattr(self, "handle_" + method)
            return await handler(payload.get("payload"), conn)
        c = await self._node_conn(node_id)
        return await c.call(method, payload.get("payload"), timeout=10)

    def _worker_inventory(self):
        return [
            {
                "worker_id": w.worker_id,
                "pid": w.pid,
                "kind": w.kind,
                "actor_id": w.actor_id.hex() if w.actor_id else None,
                "idle": w.idle,
                "node_id": self.node_id,
            }
            for w in self.workers.values()
        ]

    async def handle_list_workers(self, payload, conn):
        """Worker inventory for the state API and fault-injection
        harnesses (reference: worker listing via the dashboard state
        aggregator + `_private/test_utils.py` killer actors)."""
        return self._worker_inventory()

    async def handle_memory_table(self, payload, conn):
        """Node-level object-memory table for `rt memory` (reference:
        `ray memory` / `internal_api.py:34`): every local runtime's
        reference table plus this daemon's store occupancy and spilled
        primaries."""
        async def _one(w):
            try:
                s = await w.conn.call("memory_summary", {}, timeout=5)
            except Exception as e:
                # process died/hung mid-listing
                logger.debug("memory_summary from %s failed: %s",
                             w.worker_id[:8], e)
                return None
            s["worker_id"] = w.worker_id
            s["worker_kind"] = w.kind
            return s

        # concurrent polls: one wedged worker costs the slowest single
        # timeout, not N of them — `rt memory` gets run exactly when a
        # worker IS wedged, and the sick node must stay in the report
        live = [w for w in self.workers.values()
                if w.conn is not None and not w.conn.closed]
        procs = [
            s for s in await asyncio.gather(*[_one(w) for w in live])
            if s is not None
        ]
        with self._spill_lock:
            spilled = [i.hex() for i in self._spilled]
        store = {}
        try:
            store = {
                "used": self.store.used,
                "capacity": self.store.capacity,
            }
        except Exception as e:
            logger.debug("store stats unavailable: %s", e)
        return {
            "node_id": self.node_id,
            "store": store,
            "spilled": spilled,
            "processes": procs,
        }

    async def handle_profile_worker(self, payload, conn):
        """On-demand stack profile of one local worker (reference:
        `modules/reporter/profile_manager.py:78` py-spy dumps; here a
        pure-Python all-thread stack dump served by the worker runtime,
        with py-spy used instead when installed)."""
        w = self.workers.get(payload["worker_id"])
        if w is None:
            return {"error": "no such worker"}
        import shutil

        if payload.get("native") and shutil.which("py-spy") \
                and payload.get("mode", "stacks") == "stacks":
            # py-spy covers the one-shot dump only; flamegraph/memory
            # modes always use the in-process profilers
            proc = await asyncio.create_subprocess_exec(
                "py-spy", "dump", "--pid", str(w.pid),
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.STDOUT,
            )
            out, _ = await proc.communicate()
            return {"stacks": out.decode(errors="replace"), "pid": w.pid}
        if w.conn is None or w.conn.closed:
            return {"error": "worker not connected"}
        # mode: stacks (default, one-shot) | flamegraph (sampled CPU,
        # folded-stack output) | memory (tracemalloc window) —
        # reference: py-spy dump/record + memray in profile_manager.py
        mode = payload.get("mode", "stacks")
        duration = float(payload.get("duration_s", 5.0))
        try:
            if mode == "flamegraph":
                out = await w.conn.call(
                    "profile_cpu", {"duration_s": duration,
                                    "hz": payload.get("hz", 99.0)},
                    timeout=duration + 30,
                )
            elif mode == "memory":
                out = await w.conn.call(
                    "profile_memory", {"duration_s": duration,
                                       "top": payload.get("top", 30)},
                    timeout=duration + 30,
                )
            else:
                out = await w.conn.call("dump_stacks", None, timeout=10)
        except Exception as e:
            logger.debug("profile of %s failed: %s", w.worker_id[:8], e)
            return {"error": str(e)}
        return {"stacks": out, "pid": w.pid, "mode": mode}

    async def _fanout_once(self, method: str, payload: Dict[str, Any],
                           done=None, timeout: float = 10.0,
                           wait_reply: bool = True):
        """One-hop broadcast of a daemon method to every other alive
        daemon (with forwarded=True so peers don't re-broadcast).
        With wait_reply, stops early when `done(reply)` is truthy and
        returns that reply; otherwise fire-and-forget to all."""
        try:
            nodes = await self.controller_conn.call("get_nodes", None)
        except Exception as e:
            logger.debug("fanout get_nodes failed: %s", e)
            return None
        payload = {**payload, "forwarded": True}
        for n in nodes or []:
            if not n.get("alive") or n["node_id"] == self.node_id:
                continue
            try:
                c = await self._node_conn(n["node_id"])
                if not wait_reply:
                    c.send(method, payload)
                    continue
                reply = await c.call(method, payload, timeout=timeout)
                if done is not None and done(reply):
                    return reply
            except Exception as e:
                logger.debug("fanout %s to a peer failed: %s", method, e)
        return None

    async def handle_force_cancel_task(self, payload, conn):
        """Force-cancel: SIGKILL the worker running the task (reference:
        CancelTask force_kill).  The task's owner sees worker_died ->
        WorkerCrashedError.  Daemon-routed tasks may run anywhere:
        search locally, then forward one hop cluster-wide."""
        tid = payload["task_id"]
        for w in list(self.workers.values()):
            if tid in w.in_flight:
                try:
                    os.kill(w.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                return {"killed": True}
        if payload.get("forwarded"):
            return {"killed": False}
        reply = await self._fanout_once(
            "force_cancel_task", {"task_id": tid},
            done=lambda r: r and r.get("killed"),
        )
        return reply or {"killed": False}

    async def handle_stream_cancel(self, payload, conn):
        """Abandoned-stream stop signal for a daemon-dispatched task.
        The owner doesn't know where it runs: target the local worker
        whose in-flight set has it; if none, forward once to the other
        daemons (spillback may have moved it cluster-wide)."""
        tid = payload["task_id"]
        for w in list(self.workers.values()):
            if tid in w.in_flight and w.conn and not w.conn.closed:
                try:
                    w.conn.send("stream_cancel", {"task_id": tid})
                except Exception as e:
                    logger.debug("stream_cancel to worker failed: %s", e)
                return
        if payload.get("forwarded"):
            return  # one hop only: every daemon has now checked locally
        await self._fanout_once(
            "stream_cancel", {"task_id": tid}, wait_reply=False
        )

    async def _route_to_owner(self, owner: Tuple[str, str], method: str, payload):
        node_id, worker_id = owner
        if node_id == self.node_id:
            w = self.workers.get(worker_id)
            if w is not None and w.conn and not w.conn.closed:
                w.conn.send(method, payload)
            return
        try:
            conn = await self._node_conn(node_id)
            conn.send("route", {
                "target": owner, "method": method, "payload": payload,
                "want_reply": False,
            })
        except Exception:
            logger.warning("could not route %s to %s", method, owner)

    # ------------------------------------------------------------------
    # generic routing (owner protocol, borrows, value fetch)
    # ------------------------------------------------------------------
    async def handle_route(self, payload, conn):
        target = payload["target"]
        node_id, worker_id = target
        if node_id != self.node_id:
            c = await self._node_conn(node_id)
            if payload.get("want_reply"):
                return await c.call("route", payload)
            c.send("route", payload)
            return None
        w = self.workers.get(worker_id)
        if w is None or w.conn is None or w.conn.closed:
            if payload.get("want_reply"):
                return ("gone",)
            return None
        if payload.get("want_reply"):
            return await w.conn.call(payload["method"], payload["payload"])
        w.conn.send(payload["method"], payload["payload"])
        return None

    async def _node_conn(self, node_id: str) -> rpc.Connection:
        conn = self._node_conns.get(node_id)
        if conn is not None and not conn.closed:
            return conn
        addr = self._node_addrs.get(node_id)
        if addr is None:
            addr = await self.controller_conn.call("get_node_addr", {"node_id": node_id})
            if addr is None:
                raise rpc.RpcError(f"unknown node {node_id}")
            self._node_addrs[node_id] = tuple(addr)
        conn = await rpc.connect_tcp(
            *self._node_addrs[node_id], handler=self._handle_peer, name=f"noded->{node_id[:8]}"
        )
        self._node_conns[node_id] = conn
        return conn

    async def _handle_peer(self, method, payload, conn):
        fn = getattr(self, "handle_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"noded: no handler {method!r}")
        return await fn(payload, conn)

    # ------------------------------------------------------------------
    # object plane: transfer + free (reference: object_manager.h)
    # ------------------------------------------------------------------
    async def handle_pull_object(self, payload, conn):
        """Pull an object from a remote node into the local store,
        chunked and admission-controlled (reference: `ObjectManager`
        chunked transfer, `object_manager.h:206`; memory-bounded pull
        admission, `pull_manager.h:92`).  Concurrent pulls of the same
        object dedup onto one future; large objects stream in
        `object_transfer_chunk_bytes` pieces written straight into a
        pre-created shm buffer, so daemon RSS stays O(chunk), not
        O(object)."""
        id_bytes, node_id = payload["id"], payload["node_id"]
        if self.store.contains(id_bytes):
            return {"ok": True}
        fut = self._pulls.get(id_bytes)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._pulls[id_bytes] = fut
            try:
                await self._pull_into_store(id_bytes, node_id)
                fut.set_result(True)
            except Exception as e:
                logger.debug("pull of %s failed: %s", id_bytes.hex()[:12], e)
                fut.set_exception(e)
            finally:
                self._pulls.pop(id_bytes, None)
        await fut
        return {"ok": True}

    async def _pull_into_store(self, id_bytes: bytes, node_id: str):
        """One attempt re-fetches on checksum mismatch — a transient
        transfer corruption costs one round trip; a SECOND mismatch
        means the source's copy itself is bad, and the object is
        treated as lost (`ObjectCorruptionError` rides the error reply
        back to the owner, whose lineage path re-derives it)."""
        from ray_tpu.exceptions import ObjectCorruptionError

        c = await self._node_conn(node_id)
        chunk = self.cfg.object_transfer_chunk_bytes
        for attempt in (0, 1):
            # single round trip for the common small case: fetch_object
            # returns ("obj", bytes, crc, algo), or ("too_large", size,
            # crc, algo) when the object needs the chunked path
            reply = await c.call(
                "fetch_object", {"id": id_bytes, "max_bytes": chunk},
                timeout=120,
            )
            if reply is None:
                raise rpc.RpcError("object not on remote node")
            if isinstance(reply, tuple) and reply[0] == "too_large":
                size, crc, algo = reply[1], reply[2], reply[3]
                if await self._pull_chunked(c, id_bytes, size, crc, algo):
                    return
            else:
                data, crc, algo = (
                    reply[1:4] if isinstance(reply, tuple) else (reply, None, None)
                )
                ok = (not self.cfg.object_integrity
                      or _integrity.verify(data, crc, algo))
                if ok:
                    if not self.store.contains(id_bytes):
                        self.store.put(id_bytes, data)
                    return
            _fault_metric("rt_object_integrity_errors_total",
                          tags={"path": "transfer"})
            logger.warning(
                "object %s failed checksum on receive from %s "
                "(attempt %d)%s", id_bytes.hex()[:12], node_id[:8],
                attempt + 1, "" if attempt == 0 else "; treating as lost",
            )
        raise ObjectCorruptionError(
            f"object {id_bytes.hex()} failed checksum verification on "
            f"node-to-node receive twice; the source copy is corrupt",
        )

    async def _pull_chunked(self, c, id_bytes: bytes, size: int,
                            crc, algo) -> bool:
        """Chunked pull into a pre-created shm buffer; verifies the
        assembled object against the source's checksum BEFORE sealing.
        Returns False on checksum mismatch (buffer discarded, caller
        may retry); raises on transfer errors."""
        await self._admit_pull(size)
        try:
            try:
                dest = self.store.create(id_bytes, size)
            except ObjectExistsError:
                return True  # raced another path that materialized it
            sealed = False
            nxt = None
            chunk = self.cfg.object_transfer_chunk_bytes
            try:
                # one-ahead prefetch: the next chunk's network round
                # trip overlaps this chunk's shm memcpy
                nxt = asyncio.ensure_future(c.call(
                    "fetch_chunk",
                    {"id": id_bytes, "offset": 0, "len": chunk},
                    timeout=60,
                ))
                for off in range(0, size, chunk):
                    data = await nxt
                    nxt = None
                    next_off = off + chunk
                    if next_off < size:
                        nxt = asyncio.ensure_future(c.call(
                            "fetch_chunk",
                            {"id": id_bytes, "offset": next_off,
                             "len": min(chunk, size - next_off)},
                            timeout=60,
                        ))
                    if data is None:
                        raise rpc.RpcError(
                            "remote dropped object mid-transfer"
                        )
                    dest[off:off + len(data)] = data
                del data
                if (self.cfg.object_integrity
                        and not _integrity.verify(dest, crc, algo)):
                    return False  # finally-block discards the buffer
                self.store.seal(id_bytes)
                sealed = True
            finally:
                if nxt is not None:  # error path: reap the prefetch
                    nxt.cancel()
                del dest
                if not sealed:
                    try:
                        # abort releases the creator pin a bare delete
                        # refuses, so the partial allocation frees NOW
                        self.store.abort(id_bytes)
                    except Exception as e:
                        logger.debug("dropping unsealed %s: %s",
                                     id_bytes.hex()[:12], e)
            return True
        finally:
            self._release_pull(size)

    async def _admit_pull(self, size: int):
        """Bound total bytes of concurrent inbound transfers by what
        the store can hold (reference: pull_manager.h:92
        UpdatePullsBasedOnAvailableMemory).  At least one pull always
        proceeds so a single object larger than the budget still
        transfers (and hits the store's own create backpressure)."""
        budget = max(
            self.cfg.object_transfer_chunk_bytes,
            int(self.store.capacity * 0.5),
        )
        if self._pull_cv is None:
            self._pull_cv = asyncio.Condition()
        async with self._pull_cv:
            await self._pull_cv.wait_for(
                lambda: self._inflight_pull_bytes == 0
                or self._inflight_pull_bytes + size <= budget
            )
            self._inflight_pull_bytes += size

    def _release_pull(self, size: int):
        self._inflight_pull_bytes -= size
        if self._pull_cv is None:
            return

        async def _notify():
            async with self._pull_cv:
                self._pull_cv.notify_all()

        asyncio.ensure_future(_notify())

    async def handle_object_info(self, payload, conn):
        """Size lookup for a local object, restoring spilled primaries
        so subsequent chunk fetches can be served."""
        id_bytes = payload["id"]
        for attempt in (0, 1):
            try:
                buf = self.store.get(id_bytes, timeout_ms=0)
                try:
                    return {"size": buf.nbytes}
                finally:
                    self.store.release(id_bytes)
            except Exception as e:
                logger.debug("object %s not in store (%s); trying "
                             "spilled copy", id_bytes.hex()[:12], e)
                if attempt or not await asyncio.get_running_loop().run_in_executor(
                    None, self._restore_spilled, id_bytes
                ):
                    return None

    async def handle_fetch_chunk(self, payload, conn):
        id_bytes, off, ln = payload["id"], payload["offset"], payload["len"]
        for attempt in (0, 1):
            try:
                buf = self.store.get(id_bytes, timeout_ms=0)
            except Exception as e:
                # the object may have been spilled mid-transfer (it is
                # unpinned between chunk fetches): restore and retry
                logger.debug("chunk source %s not pinned (%s); "
                             "restoring", id_bytes.hex()[:12], e)
                if attempt or not await asyncio.get_running_loop(
                ).run_in_executor(None, self._restore_spilled, id_bytes):
                    return None
                continue
            try:
                return bytes(buf[off:off + ln])
            finally:
                self.store.release(id_bytes)
        return None

    # ------------------------------------------------------------------
    # cross-node DAG channels (reference: remote mutable objects,
    # `experimental_mutable_object_provider.h`) — the ring lives on the
    # reader's node; remote writers relay through the daemons.  The
    # blocking ring ops run in worker threads so a full ring stalls the
    # writer's pending reply, not this daemon's event loop.
    # ------------------------------------------------------------------
    async def handle_chan_remote_write(self, payload, conn):
        node_id = payload["node_id"]
        if node_id != self.node_id:
            c = await self._node_conn(node_id)
            timeout_s = payload.get("timeout_ms", 120000) / 1000.0
            return await c.call(
                "chan_remote_write", payload, timeout=timeout_s + 15
            )
        # dedicated pool: a write blocks up to its timeout while the
        # reader's ring is full — parking those on the loop's shared
        # default executor would starve every other run_in_executor
        # user (spill restores, the close that would unblock them, ...)
        if self._chan_pool is None:
            import concurrent.futures

            self._chan_pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="noded-chan"
            )
        return await asyncio.get_running_loop().run_in_executor(
            self._chan_pool, self._chan_write_local, payload
        )

    def _chan_write_local(self, payload) -> Dict[str, Any]:
        from ray_tpu.dag.channel import SPILL_KIND, ring_geometry
        from ray_tpu.shm import ChannelClosedError

        chan_h = payload["chan"]
        data = payload["payload"]
        kind = payload["kind"]
        spill_key = payload.get("spill_key")
        timeout_ms = payload.get("timeout_ms", 120000)
        # the writer ships its channel geometry so a relay that races
        # the reader's open still creates the ring with the right shape
        nslots, slot_size = ring_geometry(
            payload.get("ring_slots"), payload.get("slot_bytes")
        )
        try:
            # returns False when the ring already exists (idempotent)
            self.store.chan_create(chan_h, nslots=nslots,
                                   slot_size=slot_size)
            if spill_key is None:
                self.store.chan_write(chan_h, data, kind=kind,
                                      timeout_ms=timeout_ms)
            else:
                if self.store.contains(spill_key):
                    self.store.delete(spill_key)
                self.store.put(spill_key, data)
                try:
                    self.store.chan_write(chan_h, spill_key,
                                          kind=SPILL_KIND.get(kind, kind),
                                          timeout_ms=timeout_ms)
                except Exception:
                    self.store.delete(spill_key)
                    raise
            return {"status": "ok"}
        except ChannelClosedError:
            return {"status": "closed"}
        except TimeoutError:
            return {"status": "timeout"}
        except Exception as e:
            logger.debug("channel write failed: %s", e)
            return {"status": "error", "error": str(e)}

    async def handle_chan_remote_close(self, payload, conn):
        return await self._chan_ring_op(payload, close_only=True)

    async def handle_chan_remote_destroy(self, payload, conn):
        return await self._chan_ring_op(payload, close_only=False)

    async def _chan_ring_op(self, payload, close_only: bool):
        node_id = payload["node_id"]
        if node_id != self.node_id:
            c = await self._node_conn(node_id)
            method = "chan_remote_close" if close_only else "chan_remote_destroy"
            return await c.call(method, payload, timeout=30)

        # close/delete are non-blocking C calls (brief mutex hold): run
        # inline so they can never queue behind stalled ring writes
        try:
            self.store.chan_close(payload["chan"])
        except Exception as e:
            logger.debug("chan_close failed: %s", e)
        if not close_only:
            try:
                self.store.chan_delete(payload["chan"])
            except Exception as e:
                logger.debug("chan_delete failed: %s", e)
        return {"status": "ok"}

    async def handle_fetch_object(self, payload, conn):
        id_bytes = payload["id"]
        try:
            buf = self.store.get(id_bytes, timeout_ms=0)
        except Exception as e:
            logger.debug("meta source %s not in store (%s); trying "
                         "spilled copy", id_bytes.hex()[:12], e)
            restored = await asyncio.get_running_loop().run_in_executor(
                None, self._restore_spilled, id_bytes
            )
            if not restored:
                return None
            try:
                buf = self.store.get(id_bytes, timeout_ms=0)
            except Exception as e:
                logger.debug("restored %s still not gettable: %s",
                             id_bytes.hex()[:12], e)
                return None
        try:
            # the transfer checksum is computed fresh per fetch (never
            # cached by id: a reconstructed object can reuse its id
            # with byte-different content, and a stale cached crc
            # would poison every later transfer as "corrupt")
            crc = (_integrity.checksum(buf)
                   if self.cfg.object_integrity else None)
            algo = _integrity.ALGO if crc is not None else None
            max_bytes = payload.get("max_bytes")
            if max_bytes is not None and buf.nbytes > max_bytes:
                # chunked-transfer handshake: size + checksum, no payload
                return ("too_large", buf.nbytes, crc, algo)
            return ("obj", bytes(buf), crc, algo)
        finally:
            self.store.release(id_bytes)

    async def handle_free_object(self, payload, conn):
        self.store.delete(payload["id"])
        ent = self._spilled.pop(payload["id"], None)
        if ent is not None:
            self._remove_spill_files(ent.path)

    async def handle_free_remote(self, payload, conn):
        node_id = payload["node_id"]
        if node_id == self.node_id:
            self.store.delete(payload["id"])
            return
        try:
            c = await self._node_conn(node_id)
            c.send("free_object", {"id": payload["id"]})
        except Exception as e:
            logger.debug("free_object forward to %s failed: %s",
                         node_id[:8], e)

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    async def handle_host_actor(self, aspec: ActorCreationSpec, conn):
        """Controller asks this node to host an actor: dedicate a worker
        (reference: actor creation runs as a special task on a leased
        worker, gcs_actor_scheduler.h)."""
        demand = aspec.resources.as_dict()
        if not _fits(demand, self.available):
            return {"ok": False, "error": "resources no longer available"}
        # reserve BEFORE the wait loop so concurrent host_actor requests
        # cannot both pass the feasibility check and oversubscribe
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) - v
        tpu_n = self._tpu_chips_needed(demand)
        from ray_tpu.core.runtime_env import runtime_env_hash as _reh

        actor_env_hash = _reh(aspec.runtime_env)
        # NOT _spec_container: its env_hash fast-gate is for TaskSpecs;
        # ActorCreationSpec carries runtime_env without an env_hash
        from ray_tpu.core.container import container_section

        actor_container = container_section(aspec.runtime_env)
        target = None
        # generous: a fresh worker's first boot imports jax + the TPU
        # plugin (~10s/worker on hardware, multiplied under CPU
        # contention); 60s raced that boot and spuriously failed actor
        # creation on loaded hosts
        deadline = time.monotonic() + 240
        while target is None:
            target = self._pick_idle_worker(
                tpu_n, require_no_lease=True, env_hash=actor_env_hash,
                require_exact_env=actor_container is not None,
            )
            if target is not None and tpu_n and not self._assign_chips(
                target, tpu_n
            ):
                target = None
                self._reclaim_idle_pinned(tpu_n, actor_env_hash)
            if target is None:
                if time.monotonic() > deadline:
                    for k, v in demand.items():
                        self.available[k] = self.available.get(k, 0.0) + v
                    return {"ok": False, "error": "no idle worker"}
                if self._pending_spawns == 0:
                    try:
                        self._spawn_worker(container=(
                            (actor_env_hash, actor_container)
                            if actor_container else None
                        ))
                    except Exception as e:
                        logger.debug("actor worker spawn failed: %s", e)
                        for k, v in demand.items():
                            self.available[k] = (
                                self.available.get(k, 0.0) + v
                            )
                        return {"ok": False,
                                "error": f"worker spawn failed: {e}"}
                await asyncio.sleep(0.02)
        if actor_env_hash is not None:
            # even if __init__ fails and the worker returns to the
            # pool, its process already applied this env: tainted
            target.env_hash = actor_env_hash
        target.actor_id = aspec.actor_id.binary()
        target.lease = demand
        try:
            reply = await target.conn.call("create_actor_instance", aspec, timeout=300)
        except rpc.RemoteError as e:
            # user __init__ raised: the worker is alive — return it to
            # the pool instead of declaring it dead
            target.actor_id = None
            target.lease = None
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
            return {"ok": False, "error": f"actor __init__ failed: {e}"}
        except Exception as e:
            logger.debug("actor __init__ crashed on %s: %s",
                         target.worker_id[:8], e)
            self._on_worker_dead(target, f"actor init crashed: {e}")
            return {"ok": False, "error": f"actor __init__ failed: {e}"}
        if not reply.get("ok"):
            target.actor_id = None
            target.lease = None
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) + v
            return {"ok": False, "error": reply.get("error", "init failed")}
        self._hosted_actors[aspec.actor_id.binary()] = (
            aspec, target.worker_id
        )
        # replace the consumed pool worker (booting spawns count: see
        # the spawn-storm note in _schedule)
        free = sum(1 for w in self.workers.values()
                   if w.kind == "worker" and w.actor_id is None)
        if free + self._pending_spawns < self.num_workers:
            self._spawn_worker()
        return {"ok": True, "worker_id": target.worker_id}

    async def handle_submit_actor_task(self, payload, conn):
        spec: TaskSpec = payload["spec"]
        actor_addr = payload["actor_addr"]
        node_id, worker_id = actor_addr
        if node_id == self.node_id:
            w = self.workers.get(worker_id)
            if w is None or w.conn is None or w.conn.closed:
                result = TaskResult(task_id=spec.task_id, status="worker_died")
                await self._route_to_owner(spec.owner, "task_result", result)
                return
            w.in_flight[spec.task_id.binary()] = spec
            w.conn.send("execute_task", spec)
        else:
            c = await self._node_conn(node_id)
            c.send("submit_actor_task", payload)

    async def handle_kill_worker(self, payload, conn):
        w = self.workers.get(payload["worker_id"])
        if w is None:
            return {"ok": False}
        try:
            os.kill(w.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        return {"ok": True}

    # ------------------------------------------------------------------
    # introspection / state API
    # ------------------------------------------------------------------
    async def handle_node_stats(self, payload, conn):
        return {
            "node_id": self.node_id,
            "total_resources": self.total_resources,
            "available_resources": self.available,
            "num_workers": len([w for w in self.workers.values() if w.kind == "worker"]),
            "queued_tasks": len(self.task_queue),
            "in_flight": sum(len(w.in_flight) for w in self.workers.values()),
            "store_used": self.store.used if self.store else 0,
            "store_capacity": self.store.capacity if self.store else 0,
            "store_objects": self.store.count if self.store else 0,
            "metrics_port": self.metrics_http_port,
            # per-worker lease/blocked detail (`rt status` debugging of
            # a wedged node: WHO holds the CPUs and who is parked)
            "workers": [
                {
                    "id": w.worker_id[:8], "kind": w.kind,
                    "blocked": w.blocked, "lease": w.lease,
                    "leased_to": w.leased_to,
                    "in_flight": len(w.in_flight),
                    "actor": w.actor_id is not None,
                }
                for w in self.workers.values()
            ],
        }

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    async def shutdown(self):
        try:
            os.remove(self.socket_path)  # 'auto' discovery hygiene
        except OSError:
            pass
        if self.controller is not None:
            self.controller.flush_snapshot()
        self._draining = True
        for w in self.workers.values():
            if w.proc is not None or w.kind == "worker":
                try:
                    os.kill(w.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError) as e:
                    logger.debug("killing worker %d at shutdown: %s",
                                 w.pid, e)
        if self.unix_server:
            await self.unix_server.stop()
        if self.tcp_server:
            await self.tcp_server.stop()
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
        if self.store:
            self.store.close()
            ShmStore.unlink(self.shm_name)



def _default_store_capacity() -> int:
    try:
        import shutil

        free = shutil.disk_usage("/dev/shm").free
        return max(256 * 1024 * 1024, int(free * 0.3))
    except Exception as e:
        logger.debug("sizing /dev/shm failed (%s); using 1GiB default", e)
        return 1024 * 1024 * 1024


# ----------------------------------------------------------------------
# process entry
# ----------------------------------------------------------------------
async def _amain(args):
    logging.basicConfig(
        level=os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s noded %(levelname)s %(message)s",
    )
    daemon = NodeDaemon(
        session_dir=args.session_dir,
        is_head=args.head,
        controller_addr=tuple(args.controller.split(":")) if args.controller else None,
        num_cpus=args.num_cpus,
        num_tpus=args.num_tpus,
        resources=json.loads(args.resources) if args.resources else None,
        num_workers=args.num_workers,
        labels=json.loads(args.labels) if args.labels else None,
    )
    if daemon.controller_addr and not args.head:
        host, port = daemon.controller_addr
        daemon.controller_addr = (host, int(port))
    await daemon.start()
    if args.ready_file:
        daemon.write_ready_file(args.ready_file)

    stop = asyncio.Event()

    def _sig(*_a):
        stop.set()

    loop = asyncio.get_running_loop()
    loop.add_signal_handler(signal.SIGTERM, _sig)
    loop.add_signal_handler(signal.SIGINT, _sig)
    # exit if our parent (the driver) disappears
    ppid = os.getppid()

    async def _parent_watch():
        while True:
            await asyncio.sleep(1)
            if os.getppid() != ppid:
                stop.set()
                return

    asyncio.ensure_future(_parent_watch())
    await stop.wait()
    await daemon.shutdown()


def _primary_ip() -> str:
    """Primary interface IP (what peers on other hosts can reach when
    binding 0.0.0.0).  The UDP connect never sends a packet; it only
    asks the kernel which source address routes outward."""
    import socket

    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"


def main():
    import faulthandler

    faulthandler.register(signal.SIGUSR1, all_threads=True)
    p = argparse.ArgumentParser()
    p.add_argument("--session-dir", required=True)
    p.add_argument("--head", action="store_true")
    p.add_argument("--controller", default=None, help="host:port when joining")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--resources", default=None, help="json dict")
    p.add_argument("--labels", default=None, help="json dict of node labels")
    p.add_argument("--num-workers", type=int, default=0)
    p.add_argument("--ready-file", default=None)
    args = p.parse_args()
    os.makedirs(os.path.join(args.session_dir, "logs"), exist_ok=True)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
