"""Task and actor specifications.

Equivalent of the reference's `TaskSpecification`
(`src/ray/common/task/task_spec.h`): everything the executing side needs
to run a task — function identity, resolved/unresolved args, resource
demands, retry policy, actor linkage, scheduling strategy.

Functions ship by content hash through the controller's function store
(reference: `_private/function_manager.py` exporting via GCS KV) so a
function is transferred to each node at most once, not per-task.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, ObjectID, PlacementGroupID, TaskID, WorkerID


# num_returns sentinel for streaming-generator tasks (the API-level
# num_returns="streaming"): return objects are created one per yielded
# item instead of ahead of execution.
STREAMING = -1


def function_id_of(blob: bytes) -> bytes:
    return hashlib.sha256(blob).digest()[:16]


def fits(demand: Dict[str, float], supply: Dict[str, float]) -> bool:
    """Resource feasibility with float-dust tolerance; shared by the
    controller and node daemons so both agree on schedulability."""
    return all(supply.get(k, 0.0) >= v - 1e-9 for k, v in demand.items() if v > 0)


@dataclass
class ArgRef:
    """Marker for a top-level ObjectRef argument to be resolved by the
    executor (reference: dependency_resolver.h resolution + plasma args)."""

    id_bytes: bytes
    owner: Optional[Tuple[str, str]]


@dataclass
class Resources:
    """Resource demand; values are floats like the reference's resource
    set (`src/ray/common/scheduling/resource_set.h`).  TPU chips are a
    predefined resource, not a custom string."""

    num_cpus: float = 1.0
    num_tpus: float = 0.0
    memory: float = 0.0
    custom: Dict[str, float] = field(default_factory=dict)

    def as_dict(self) -> Dict[str, float]:
        d = dict(self.custom)
        if self.num_cpus:
            d["CPU"] = self.num_cpus
        if self.num_tpus:
            d["TPU"] = self.num_tpus
        if self.memory:
            d["memory"] = self.memory
        return d

    @staticmethod
    def from_options(opts: Dict[str, Any]) -> "Resources":
        res = dict(opts.get("resources") or {})
        num_tpus = opts.get("num_tpus", res.pop("TPU", 0.0)) or 0.0
        if num_tpus:
            from ray_tpu.core.accelerators import validate_chip_request

            err = validate_chip_request(float(num_tpus))
            if err:
                raise ValueError(err)
        return Resources(
            num_cpus=opts.get("num_cpus", 1.0) or 0.0,
            num_tpus=num_tpus,
            memory=opts.get("memory", 0.0) or 0.0,
            custom=res,
        )


@dataclass
class SchedulingStrategy:
    """Placement constraints (reference: `util/scheduling_strategies.py`).

    kind: "default" | "spread" | "node_affinity" | "placement_group"
         | "node_labels"
    """

    kind: str = "default"
    node_id: Optional[str] = None
    soft: bool = False
    pg_id: Optional[bytes] = None
    pg_bundle_index: int = -1
    pg_capture_child_tasks: bool = False
    # label expressions for kind="node_labels": lists of
    # (key, op, values) with op in {"in","not_in","exists","does_not_exist"}
    # (reference: `util/scheduling_strategies.py:135`
    # NodeLabelSchedulingStrategy hard/soft expression maps)
    label_hard: Optional[List[Tuple[str, str, List[str]]]] = None
    label_soft: Optional[List[Tuple[str, str, List[str]]]] = None
    # set when a daemon already routed this task via the controller's
    # label-aware pick: the receiving daemon queues locally instead of
    # re-routing (keeps daemon-to-daemon forwards one-hop while the
    # label constraints stay attached for label-aware spillback)
    label_routed: bool = False


def match_labels(exprs, labels: Dict[str, str]) -> bool:
    """True when every (key, op, values) expression holds for `labels`
    (reference semantics: `node_label_scheduling_policy.h:25`)."""
    for key, op, values in exprs or []:
        present = key in labels
        if op == "exists":
            if not present:
                return False
        elif op == "does_not_exist":
            if present:
                return False
        elif op == "in":
            if not present or labels[key] not in values:
                return False
        elif op == "not_in":
            if present and labels[key] in values:
                return False
        else:
            raise ValueError(f"unknown label operator: {op}")
    return True


@dataclass
class TaskSpec:
    task_id: TaskID
    function_id: bytes
    # small function blobs ride in the spec on first submission; the
    # executor caches by function_id and later specs omit it
    function_blob: Optional[bytes]
    args: List[Any]  # positional: raw values or ArgRef markers
    kwargs: Dict[str, Any]
    num_returns: int
    owner: Tuple[str, str]  # (node_id_hex, worker_id_hex)
    resources: Resources
    max_retries: int = 3
    retry_exceptions: bool = False
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    name: str = ""
    # actor linkage
    actor_id: Optional[ActorID] = None  # actor task if set
    seq_no: int = -1  # per-caller submission order for actor tasks
    # opt-in tracing context {trace_id, span_id} (reference: trace
    # propagation in task metadata, `tracing_helper.py:165`)
    trace_ctx: Optional[Dict[str, str]] = None
    # per-task runtime env (reference: task runtime_env via dedicated
    # workers keyed by env hash, `worker_pool.h` runtime-env matching);
    # env_hash precomputed at submit so daemons never re-hash
    runtime_env: Optional[Dict[str, Any]] = None
    env_hash: Optional[str] = None
    # end-to-end deadline (`.options(timeout_s=...)`), as an ABSOLUTE
    # local `time.monotonic()` instant.  Monotonic clocks don't travel:
    # the wire carries `deadline_remaining_s` (budget left at encode
    # time) and the decoder re-anchors to its own clock, so every relay
    # hop shrinks the budget by its own transit time — gRPC-style
    # deadline propagation.
    deadline_s: Optional[float] = None

    @property
    def deadline_remaining_s(self) -> Optional[float]:
        """Budget remaining right now (wire representation of the
        deadline; recomputed at every encode, so retries/relays carry
        the honestly-shrunk budget)."""
        if self.deadline_s is None:
            return None
        import time

        return self.deadline_s - time.monotonic()

    def deadline_expired(self) -> bool:
        if self.deadline_s is None:
            return False
        import time

        return time.monotonic() >= self.deadline_s

    def return_ids(self) -> List[ObjectID]:
        if self.num_returns == STREAMING:
            return []  # item ids are appended dynamically as yielded
        return [ObjectID.for_return(self.task_id, i + 1) for i in range(self.num_returns)]

    @property
    def is_streaming(self) -> bool:
        return self.num_returns == STREAMING


def task_spec_from_wire(**fields) -> "TaskSpec":
    """Wire-decode constructor: converts the on-wire remaining budget
    back into an absolute deadline on THIS process's monotonic clock."""
    remaining = fields.pop("deadline_remaining_s", None)
    spec = TaskSpec(**fields)
    if remaining is not None:
        import time

        spec.deadline_s = time.monotonic() + remaining
    return spec


@dataclass
class ActorCreationSpec:
    actor_id: ActorID
    class_id: bytes
    class_blob: Optional[bytes]
    init_args: List[Any]
    init_kwargs: Dict[str, Any]
    owner: Tuple[str, str]
    resources: Resources
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    is_async: bool = False
    name: Optional[str] = None  # named actor (reference: get_actor)
    namespace: str = "default"
    # method names defined as (async) generators — recorded so handles
    # rebuilt via get_actor stream them too (reference: method metadata
    # in the GCS actor table)
    streaming_methods: Tuple[str, ...] = ()
    # named execution lanes with per-group concurrency limits
    # (reference: `core_worker/transport/concurrency_group_manager.h`);
    # calls pick a lane via `.options(concurrency_group=...)` or the
    # @rt.method default recorded in method_groups
    concurrency_groups: Optional[Dict[str, int]] = None
    method_groups: Optional[Dict[str, str]] = None
    # computed once at the driver (the raw predicate before it folds
    # into is_async): the executor's default-lane policy depends on it
    has_async_methods: bool = False
    # opt-out of per-caller in-order delivery (reference:
    # `out_of_order_actor_scheduling_queue.h:37`): tasks execute as
    # they arrive, so a slow earlier call never delays a later one
    allow_out_of_order: bool = False
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    lifetime: Optional[str] = None  # "detached" keeps it past driver exit
    # {"env_vars": {...}, "working_dir": path} applied in the actor's
    # dedicated worker before __init__ (reference: _private/runtime_env/;
    # actors own their worker, so process-level env mutation is safe —
    # pooled task workers are shared and don't support this)
    runtime_env: Optional[Dict[str, Any]] = None


@dataclass
class TaskResult:
    """Sent executor -> owner when a task finishes.

    Small return values are inlined (reference: direct returns into the
    caller's in-process memory store); large ones were sealed into the
    executor node's shm store and only (object_id, node_id, size) travels.
    """

    task_id: TaskID
    status: str  # "ok" | "error" | "worker_died"
    # per-return: ("inline", bytes) or ("shm", node_id_hex, size)
    returns: List[Tuple] = field(default_factory=list)
    error: Optional[bytes] = None  # serialized TaskError envelope
    execution_info: Dict[str, float] = field(default_factory=dict)


@dataclass
class TaskResultBatch:
    """Coalesced completion frame: every TaskResult one executor
    produced for ONE owner within one event-loop tick, shipped as a
    single wire frame (reference analog: the reply batching gRPC's
    HTTP/2 framing gives the raylet for free; here the win is on the
    OWNER side — one frame means one dispatch task and one
    drain/lease pass for the whole batch instead of per task)."""

    owner: Tuple[str, str]
    results: List[TaskResult] = field(default_factory=list)
