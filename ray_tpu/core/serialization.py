"""Object serialization.

Equivalent of the reference's `python/ray/_private/serialization.py`:
cloudpickle for arbitrary Python objects, zero-copy numpy via pickle
protocol-5 out-of-band buffers, ObjectRefs captured in-band and surfaced
so the reference counter can track borrows, and task errors wrapped in a
typed envelope that `get` re-raises.

Wire format of a stored object:
    [1 byte tag][4 bytes LE meta_len][meta pickle][buffer data...]
where meta contains the in-band pickle plus (offset, length) table for
out-of-band buffers, which follow contiguously (64-byte aligned) so
numpy arrays deserialize as views over shared memory without a copy.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

TAG_DATA = 0
TAG_ERROR = 1  # payload is a pickled exception (TaskError envelope)

_ALIGN = 64

# Registered custom (reducer, reconstructor) pairs, keyed by type —
# the `util/serialization.py` register_serializer surface.
_custom_serializers: dict = {}


def register_serializer(cls, *, serializer: Callable, deserializer: Callable):
    _custom_serializers[cls] = (serializer, deserializer)


def deregister_serializer(cls):
    _custom_serializers.pop(cls, None)


class _Pickler(cloudpickle.CloudPickler):
    def __init__(self, file, protocol=5, buffer_callback=None, refs=None):
        super().__init__(file, protocol=protocol, buffer_callback=buffer_callback)
        self._captured_refs = refs

    def persistent_id(self, obj):  # noqa: D401 - pickler hook
        return None

    def reducer_override(self, obj):
        # Capture ObjectRefs in-band; record them for borrower tracking.
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            if self._captured_refs is not None:
                self._captured_refs.append(obj)
            return (ObjectRef._deserialize, (obj._serialize_args(),))
        ser = _custom_serializers.get(type(obj))
        if ser is not None:
            serializer, deserializer = ser
            return (deserializer, (serializer(obj),))
        return super().reducer_override(obj)


def serialize(
    value: Any, tag: int = TAG_DATA
) -> Tuple[List[memoryview], int, List["Any"]]:
    """Serialize to (chunks, total_size, captured_object_refs).

    chunks is a list of buffers to be written contiguously; numpy/jax
    host arrays travel as raw out-of-band buffers (no copy on write if
    the caller writes straight into shm).
    """
    import io

    buffers: List[pickle.PickleBuffer] = []
    refs: List[Any] = []
    f = io.BytesIO()
    p = _Pickler(f, protocol=5, buffer_callback=buffers.append, refs=refs)
    p.dump(value)
    inband = f.getvalue()

    raw = [b.raw() for b in buffers]
    # layout: header | meta | pad | buf0 | pad | buf1 ...
    offsets = []
    meta_payload = pickle.dumps((inband, [len(r) for r in raw]), protocol=5)
    header = struct.pack("<BI", tag, len(meta_payload))
    pos = len(header) + len(meta_payload)
    chunks: List[memoryview] = [memoryview(header), memoryview(meta_payload)]
    for r in raw:
        pad = (-pos) % _ALIGN
        if pad:
            chunks.append(memoryview(b"\x00" * pad))
            pos += pad
        offsets.append(pos)
        chunks.append(r)
        pos += r.nbytes
    # offsets are recomputed at load from lengths; nothing else needed
    return chunks, pos, refs


def serialize_to_bytes(value: Any, tag: int = TAG_DATA) -> bytes:
    chunks, total, _refs = serialize(value, tag)
    out = bytearray(total)
    pos = 0
    for c in chunks:
        out[pos : pos + c.nbytes] = c
        pos += c.nbytes
    return bytes(out)


_PARALLEL_COPY_MIN = 16 * 1024 * 1024
# parallel memcpy only helps with cores to run it: on a 1-2 vCPU box
# the thread fan-out costs ~7x on fresh tmpfs pages (page-fault path is
# kernel-serialized; threads just thrash the core) — measured 0.14 GB/s
# with 6 workers vs 1.0 GB/s single-threaded on 1 vCPU
_COPY_WORKERS = max(1, min(6, (os.cpu_count() or 1) - 1))
_copy_pool = None


def _parallel_copy(dest: memoryview, src: memoryview) -> None:
    """Multi-threaded memcpy for big buffers.  NumPy releases the GIL
    around large copy loops, so slicing the range across a small thread
    pool multiplies effective bandwidth (reference: plasma's
    `memcopy_threads` parallel memcpy for large object creates)."""
    global _copy_pool
    import concurrent.futures

    import numpy as np

    if _copy_pool is None:
        _copy_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=_COPY_WORKERS, thread_name_prefix="rt-memcpy"
        )
    d = np.frombuffer(dest, dtype=np.uint8)
    s = np.frombuffer(src, dtype=np.uint8)
    n = len(s)
    step = (n + _COPY_WORKERS - 1) // _COPY_WORKERS
    futs = [
        _copy_pool.submit(np.copyto, d[i : i + step], s[i : i + step])
        for i in range(0, n, step)
    ]
    for f in futs:
        f.result()


def write_chunks(chunks: List[memoryview], dest: memoryview):
    pos = 0
    for c in chunks:
        if (_COPY_WORKERS > 1 and c.nbytes >= _PARALLEL_COPY_MIN
                and c.contiguous):
            _parallel_copy(dest[pos : pos + c.nbytes], c)
        else:
            dest[pos : pos + c.nbytes] = c
        pos += c.nbytes


def deserialize(buf: memoryview) -> Tuple[int, Any]:
    """Deserialize from a contiguous buffer (zero-copy for array data).

    Returns (tag, value).  The returned value may hold views into
    ``buf`` — the caller manages the pin lifetime.
    """
    buf = memoryview(buf).cast("B")
    tag, meta_len = struct.unpack_from("<BI", buf, 0)
    hdr = 5
    inband, lengths = pickle.loads(buf[hdr : hdr + meta_len])
    pos = hdr + meta_len
    out_of_band = []
    for ln in lengths:
        pad = (-pos) % _ALIGN
        pos += pad
        out_of_band.append(buf[pos : pos + ln])
        pos += ln
    value = pickle.loads(inband, buffers=out_of_band)
    return tag, value


def dumps_oob(value: Any) -> bytes:
    """Plain cloudpickle for control-plane payloads (no buffer split)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data) -> Any:
    return pickle.loads(data)
