"""Schema'd control-plane wire codec (versioned, no-pickle).

Reference analog: `src/ray/protobuf/*.proto` (24 files) — every
control-plane message has a typed schema and a protocol version, and
peers reject version mismatches at connect time.  The reference
compiles protobufs; here the codec is a small tagged binary format with
a per-class field registry, which buys the same properties without a
compiler step:

- **No pickle on the control path.**  `decode` never unpickles: only
  plain data (None/bool/int/float/str/bytes/list/tuple/dict/set),
  registered schema classes (encoded as field lists), and exceptions
  rebuilt from an allowlist (ray_tpu.* and builtins).  User payloads
  (task args, function blobs, object values) ride as OPAQUE BYTES
  produced by the serialization layer and are deserialized only at
  their consumer — the worker executing the task — never by relaying
  daemons.
- **Versioned.**  `PROTOCOL_VERSION` rides in the connection handshake
  (`rpc.py`); a mismatched peer is rejected cleanly before any payload
  decodes.
- **Schema'd.**  Control dataclasses (TaskSpec, TaskResult, Resources,
  ActorCreationSpec, ...) register field lists; unknown fields from a
  newer minor revision are ignored on decode and missing fields take
  the dataclass default (forward/backward compat within a major
  version).

`encode` raises `WireError` for values outside the model — the rpc
layer then falls back to a cloudpickle frame marked with a distinct
codec id, which daemons can be configured to refuse
(`wire_require_schema`); the escape hatch exists for out-of-tree
extensions, never for the core protocol.
"""

from __future__ import annotations

import logging
import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

PROTOCOL_VERSION = 1

# type tags
_NONE = 0x00
_TRUE = 0x01
_FALSE = 0x02
_INT = 0x03
_FLOAT = 0x04
_STR = 0x05
_BYTES = 0x06
_LIST = 0x07
_TUPLE = 0x08
_DICT = 0x09
_SET = 0x0A
_SCHEMA = 0x0B
_EXC = 0x0C

_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")


class WireError(Exception):
    pass


class SchemaRegistry:
    def __init__(self):
        self.by_cls: Dict[type, Tuple[str, Tuple[str, ...]]] = {}
        self.by_name: Dict[str, Tuple[Callable, frozenset]] = {}

    def register(self, cls: type, fields, construct: Optional[Callable] = None,
                 name: str = ""):
        """`fields` are attribute names encoded in order; decode calls
        `construct(**present_known_fields)` (default: the class itself,
        with dataclass defaults covering missing fields)."""
        n = name or cls.__name__
        self.by_cls[cls] = (n, tuple(fields))
        self.by_name[n] = (construct or cls, frozenset(fields))
        return cls


registry = SchemaRegistry()


def _encode(out: List[bytes], v: Any):
    t = type(v)
    if v is None:
        out.append(b"\x00")
    elif v is True:
        out.append(b"\x01")
    elif v is False:
        out.append(b"\x02")
    elif t is int:
        if not -(2**63) <= v < 2**63:
            raise WireError(f"int out of i64 range: {v}")
        out.append(b"\x03")
        out.append(_I64.pack(v))
    elif t is float:
        out.append(b"\x04")
        out.append(_F64.pack(v))
    elif t is str:
        b = v.encode()
        out.append(b"\x05" + _U32.pack(len(b)) + b)
    elif t in (bytes, bytearray, memoryview):
        b = bytes(v)
        out.append(b"\x06" + _U32.pack(len(b)) + b)
    elif t is list:
        out.append(b"\x07" + _U32.pack(len(v)))
        for x in v:
            _encode(out, x)
    elif t is tuple:
        out.append(b"\x08" + _U32.pack(len(v)))
        for x in v:
            _encode(out, x)
    elif t is dict:
        out.append(b"\x09" + _U32.pack(len(v)))
        for k, x in v.items():
            _encode(out, k)
            _encode(out, x)
    elif t in (set, frozenset):
        out.append(b"\x0a" + _U32.pack(len(v)))
        for x in v:
            _encode(out, x)
    else:
        ent = registry.by_cls.get(t)
        if ent is not None:
            name, fields = ent
            nb = name.encode()
            out.append(b"\x0b" + _U32.pack(len(nb)) + nb + _U32.pack(len(fields)))
            for f in fields:
                fb = f.encode()
                out.append(_U32.pack(len(fb)) + fb)
                _encode(out, getattr(v, f))
        elif isinstance(v, BaseException):
            et = type(v)
            out.append(b"\x0c")
            _encode(out, (
                et.__module__, et.__qualname__,
                [a if _is_plain(a) else repr(a) for a in v.args],
            ))
        else:
            raise WireError(
                f"type {t.__module__}.{t.__qualname__} is not "
                f"wire-encodable (register a schema or pass bytes)"
            )


def _is_plain(v) -> bool:
    return v is None or type(v) in (bool, int, float, str, bytes)


def _exc_allowed(module: str, qualname: str) -> Optional[type]:
    """Exception classes reconstructable on decode: ray_tpu's own and
    builtins only — never arbitrary imports."""
    if module == "builtins":
        import builtins

        t = getattr(builtins, qualname, None)
    elif module == "ray_tpu.exceptions" or module == "ray_tpu.core.rpc":
        import importlib

        try:
            mod = importlib.import_module(module)
        except Exception as e:
            logger.debug("exception allowlist import %s failed: %s", module, e)
            return None
        t = mod
        for part in qualname.split("."):
            t = getattr(t, part, None)
            if t is None:
                return None
    else:
        return None
    if isinstance(t, type) and issubclass(t, BaseException):
        return t
    return None


# schema + field names repeat on every frame: intern them (bounded by
# the set of distinct identifiers actually used on the wire)
_name_cache: Dict[bytes, str] = {}


def _intern(b: bytes) -> str:
    s = _name_cache.get(b)
    if s is None:
        if len(_name_cache) > 4096:
            _name_cache.clear()
        s = _name_cache[b] = b.decode()
    return s


def _decode(buf: bytes, pos: int) -> Tuple[Any, int]:
    """Returns (value, new_pos).  Operates on bytes with explicit
    offsets — the hot path of every daemon relay, so no reader-object
    indirection and no per-field memoryview churn."""
    tag = buf[pos]
    pos += 1
    if tag == _STR:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        if pos + n > len(buf):
            raise WireError("truncated frame")
        return buf[pos : pos + n].decode(), pos + n
    if tag == _BYTES:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        if pos + n > len(buf):
            raise WireError("truncated frame")
        return buf[pos : pos + n], pos + n
    if tag == _INT:
        return _I64.unpack_from(buf, pos)[0], pos + 8
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _FLOAT:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == _LIST or tag == _TUPLE or tag == _SET:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        out = []
        for _ in range(n):
            v, pos = _decode(buf, pos)
            out.append(v)
        if tag == _LIST:
            return out, pos
        return (tuple(out) if tag == _TUPLE else set(out)), pos
    if tag == _DICT:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _decode(buf, pos)
            v, pos = _decode(buf, pos)
            d[k] = v
        return d, pos
    if tag == _SCHEMA:
        n = _U32.unpack_from(buf, pos)[0]
        pos += 4
        name = _intern(buf[pos : pos + n])
        pos += n
        nf = _U32.unpack_from(buf, pos)[0]
        pos += 4
        fields = {}
        for _ in range(nf):
            ln = _U32.unpack_from(buf, pos)[0]
            pos += 4
            fname = _intern(buf[pos : pos + ln])
            pos += ln
            v, pos = _decode(buf, pos)
            fields[fname] = v
        ent = registry.by_name.get(name)
        if ent is None:
            raise WireError(f"unknown schema {name!r}")
        construct, known = ent
        if not known.issuperset(fields):
            # forward compat: drop fields a newer peer added
            fields = {k: v for k, v in fields.items() if k in known}
        try:
            return construct(**fields), pos
        except WireError:
            raise
        except Exception as e:
            # a corrupt frame can hand a construct hook fields of the
            # wrong shape/type; whatever it raises is a decode failure
            raise WireError(
                f"schema {name!r} construct failed: {e!r}"
            ) from None
    if tag == _EXC:
        (module, qualname, args), pos = _decode(buf, pos)
        t = _exc_allowed(module, qualname)
        if t is not None:
            try:
                return t(*args), pos
            except Exception as e:
                logger.debug(
                    "rebuilding %s.%s%r failed (%s); degrading to RpcError",
                    module, qualname, tuple(args), e,
                )
        from ray_tpu.core import rpc as _rpc

        return _rpc.RpcError(f"{module}.{qualname}{tuple(args)!r}"), pos
    raise WireError(f"bad wire tag 0x{tag:02x}")


def encode(v: Any) -> bytes:
    out: List[bytes] = []
    _encode(out, v)
    return b"".join(out)


def decode(data) -> Any:
    """Decode one wire value.  Every malformed input — truncation,
    bit flips, corrupted length fields, absurd nesting — raises
    `WireError` (fuzz-gated in tests/test_wire_fuzz.py): corrupt
    bytes can surface garbage *values* of valid types, but never a
    hang, an unbounded allocation, or an untyped exception.
    TypeError/ValueError cover flips that survive tag parsing and
    die inside a container or schema constructor (an unhashable set
    element, a field of the wrong type); RecursionError covers a
    flipped byte stamping out deeply nested container tags."""
    buf = bytes(data)
    try:
        v, pos = _decode(buf, 0)
    except (IndexError, struct.error):
        raise WireError("truncated frame") from None
    except UnicodeDecodeError as e:
        raise WireError(f"corrupt string field: {e}") from None
    except RecursionError:
        raise WireError("frame nests too deeply") from None
    except (TypeError, ValueError, OverflowError) as e:
        raise WireError(f"corrupt frame: {e!r}") from None
    if pos != len(buf):
        raise WireError("trailing bytes after value")
    return v


# ----------------------------------------------------------------------
# core schema registrations (the ~20 control-plane message classes)
# ----------------------------------------------------------------------
_registered = False


def register_core_schemas():
    global _registered
    if _registered:
        return
    _registered = True
    from ray_tpu.core import ids as _ids
    from ray_tpu.core import task_spec as _ts

    def _id_construct(cls):
        return lambda **kw: cls(kw["_bytes"])

    for cls in (_ids.JobID, _ids.TaskID, _ids.ObjectID, _ids.ActorID,
                _ids.WorkerID, _ids.PlacementGroupID):
        registry.register(cls, ["_bytes"], construct=_id_construct(cls))

    registry.register(_ts.ArgRef, ["id_bytes", "owner"])
    registry.register(_ts.Resources,
                      ["num_cpus", "num_tpus", "memory", "custom"])
    registry.register(_ts.SchedulingStrategy,
                      ["kind", "node_id", "soft", "pg_id",
                       "pg_bundle_index", "pg_capture_child_tasks",
                       "label_hard", "label_soft", "label_routed"])
    # `deadline_remaining_s` is a computed property (budget left at
    # encode time); the construct hook re-anchors it to the decoder's
    # monotonic clock (gRPC-style deadline propagation)
    registry.register(_ts.TaskSpec, [
        "task_id", "function_id", "function_blob", "args", "kwargs",
        "num_returns", "owner", "resources", "max_retries",
        "retry_exceptions", "strategy", "name", "actor_id", "seq_no",
        "trace_ctx", "runtime_env", "env_hash", "deadline_remaining_s",
    ], construct=_ts.task_spec_from_wire)
    registry.register(_ts.ActorCreationSpec, [
        "actor_id", "class_id", "class_blob", "init_args", "init_kwargs",
        "owner", "resources", "max_restarts", "max_task_retries",
        "max_concurrency", "is_async", "name", "namespace",
        "streaming_methods", "strategy", "lifetime", "runtime_env",
        "concurrency_groups", "method_groups", "allow_out_of_order",
        "has_async_methods",
    ])
    registry.register(_ts.TaskResult, [
        "task_id", "status", "returns", "error", "execution_info",
    ])
    # coalesced completion frame (owner-sharded control plane): one
    # frame per (executor connection, owner) per event-loop tick
    registry.register(_ts.TaskResultBatch, ["owner", "results"])
