"""Placement groups with TPU-topology-aware bundle packing.

Reference: `gcs_placement_group_manager.h` + bundle policies
PACK/SPREAD/STRICT_PACK/STRICT_SPREAD
(`bundle_scheduling_policy.h:31-106`).  TPU-first inversion (SURVEY §7):
the unit of gang placement is an ICI-connected slice — STRICT_PACK means
"one ICI domain", expressed here through node labels
(`tpu-slice`: nodes in the same slice share a label value), not just
"one machine".
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.core.ids import PlacementGroupID

logger = logging.getLogger(__name__)


@dataclass
class PlacementGroupInfo:
    pg_id: bytes
    bundles: List[Dict[str, float]]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    state: str = "PENDING"  # PENDING | CREATED | REMOVED
    # bundle index -> node_id
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    name: str = ""
    ready_event: Optional[asyncio.Event] = None


class PlacementGroupManager:
    """Lives in the controller; reserves bundle resources on nodes.

    Two-phase commit like the reference scheduler
    (`gcs_placement_group_scheduler.h`): prepare (reserve on all nodes)
    then commit; any failure rolls back all reservations.
    """

    def __init__(self, controller):
        self.controller = controller
        self.groups: Dict[bytes, PlacementGroupInfo] = {}
        controller.placement_groups = self.groups
        # rehydrate groups persisted by a previous controller
        # incarnation (reference: GcsInitData placement-group table) —
        # reservations re-apply per node in handle_register_node as
        # daemons (re)register with full capacity
        for pid_hex, d in getattr(controller, "_rehydrated_pgs",
                                  {}).items():
            info = PlacementGroupInfo(
                pg_id=bytes.fromhex(pid_hex),
                bundles=[dict(b) for b in d["bundles"]],
                strategy=d["strategy"],
                state=d["state"],
                bundle_nodes=list(d["bundle_nodes"]),
                name=d.get("name", ""),
                ready_event=asyncio.Event(),
            )
            if info.state == "CREATED":
                info.ready_event.set()
            self.groups[info.pg_id] = info
        controller._rehydrated_pgs = {}

    async def create(self, pg_id: bytes, bundles, strategy: str, name: str = "") -> PlacementGroupInfo:
        info = PlacementGroupInfo(
            pg_id=pg_id,
            bundles=[dict(b) for b in bundles],
            strategy=strategy,
            bundle_nodes=[None] * len(bundles),
            name=name,
            ready_event=asyncio.Event(),
        )
        self.groups[pg_id] = info
        self._try_place(info)
        return info

    def _try_place(self, info: PlacementGroupInfo) -> bool:
        placed = self._plan(info)
        if placed is None:
            info.state = "PENDING"  # retried when resources appear
            return False
        # reserve: decrement controller's view of node resources
        for idx, node_id in enumerate(placed):
            node = self.controller.nodes[node_id]
            for k, v in info.bundles[idx].items():
                node.resources[k] = node.resources.get(k, 0.0) - v
        info.bundle_nodes = placed
        info.state = "CREATED"
        info.ready_event.set()
        self.controller._mark_dirty()
        return True

    def retry_pending(self):
        """Re-plan PENDING groups; called when capacity appears (node
        registration, PG removal) — reference: the PG manager's retry
        queue (`gcs_placement_group_manager.h` pending queue)."""
        for info in list(self.groups.values()):
            if info.state == "PENDING":
                self._try_place(info)

    def _plan(self, info: PlacementGroupInfo) -> Optional[List[str]]:
        nodes = [n for n in self.controller.nodes.values() if n.alive]
        avail = {n.node_id: dict(n.resources) for n in nodes}

        def take(node_id, bundle) -> bool:
            a = avail[node_id]
            if all(a.get(k, 0.0) >= v for k, v in bundle.items()):
                for k, v in bundle.items():
                    a[k] = a.get(k, 0.0) - v
                return True
            return False

        s = info.strategy
        if s in ("PACK", "STRICT_PACK"):
            # try to fit all bundles into one ICI domain (same tpu-slice
            # label), else one node, else (PACK only) spill across nodes
            domains: Dict[str, List] = {}
            for n in nodes:
                key = n.labels.get("tpu-slice", n.node_id)
                domains.setdefault(key, []).append(n)
            for _key, group in sorted(
                domains.items(), key=lambda kv: -len(kv[1])
            ):
                trial = {n.node_id: dict(avail[n.node_id]) for n in group}
                placed: List[Optional[str]] = []
                ok = True
                for b in info.bundles:
                    hit = None
                    for n in group:
                        a = trial[n.node_id]
                        if all(a.get(k, 0.0) >= v for k, v in b.items()):
                            for k, v in b.items():
                                a[k] = a.get(k, 0.0) - v
                            hit = n.node_id
                            break
                    if hit is None:
                        ok = False
                        break
                    placed.append(hit)
                if ok:
                    return placed
            if s == "STRICT_PACK":
                return None
            # PACK fallback: greedy anywhere
            placed = []
            for b in info.bundles:
                hit = next((nid for nid in avail if take(nid, b)), None)
                if hit is None:
                    return None
                placed.append(hit)
            return placed
        if s in ("SPREAD", "STRICT_SPREAD"):
            placed = []
            used_nodes = set()
            for b in info.bundles:
                choice = None
                # prefer unused nodes
                for nid in sorted(avail, key=lambda x: x in used_nodes):
                    if s == "STRICT_SPREAD" and nid in used_nodes:
                        continue
                    if take(nid, b):
                        choice = nid
                        break
                if choice is None:
                    return None
                used_nodes.add(choice)
                placed.append(choice)
            return placed
        raise ValueError(f"unknown placement strategy {s!r}")

    def node_for_bundle(self, pg_id: bytes, bundle_index: int) -> Optional[str]:
        info = self.groups.get(pg_id)
        if info is None or info.state != "CREATED":
            return None
        if bundle_index < 0:
            return info.bundle_nodes[0] if info.bundle_nodes else None
        return info.bundle_nodes[bundle_index]

    def remove(self, pg_id: bytes):
        info = self.groups.pop(pg_id, None)
        if info is None or info.state != "CREATED":
            return
        for idx, node_id in enumerate(info.bundle_nodes):
            node = self.controller.nodes.get(node_id)
            if node is not None:
                for k, v in info.bundles[idx].items():
                    node.resources[k] = node.resources.get(k, 0.0) + v
        info.state = "REMOVED"
        self.controller._mark_dirty()
        self.retry_pending()
