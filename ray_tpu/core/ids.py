"""Binary identifiers with embedded lineage.

Design follows the reference's nested-ID scheme (`src/ray/common/id.h`,
`id_def.h`): a JobID is embedded in every TaskID, and an ObjectID is its
creating TaskID plus a return/put index — so ownership and lineage can be
recovered from the bits of an ID alone, with no directory lookup.

Layout (bytes):
    JobID    = 4 random bytes
    ActorID  = JobID(4) + 8 random          -> 12
    TaskID   = JobID(4) + 10 random         -> 14  (actor tasks embed ActorID)
    ObjectID = TaskID(14) + 4 LE index      -> 18
    NodeID / WorkerID / PlacementGroupID = 14 random bytes

IDs are immutable, hashable, and cheap to pickle (they serialize as raw
bytes).  Hex forms are used in logs and the state API.
"""

from __future__ import annotations

import os
import struct
import threading

_JOB_LEN = 4
_ACTOR_LEN = 12
_TASK_LEN = 14
_OBJECT_LEN = 18
_UNIQUE_LEN = 14

# Index space for object ids: returns are 1..MAX_RETURNS, puts are
# MAX_RETURNS+1.. (mirrors the reference's put/return index split,
# `src/ray/common/id.h` ObjectID::FromIndex).
MAX_RETURNS = 1 << 24
_PUT_BASE = MAX_RETURNS


class BaseID:
    __slots__ = ("_bytes",)
    _LEN = 0

    def __init__(self, b: bytes):
        if len(b) != self._LEN:
            raise ValueError(
                f"{type(self).__name__} requires {self._LEN} bytes, got {len(b)}"
            )
        self._bytes = bytes(b)

    @classmethod
    def random(cls) -> "BaseID":
        return cls(os.urandom(cls._LEN))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls._LEN)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * self._LEN

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    @classmethod
    def from_hex(cls, h: str) -> "BaseID":
        return cls(bytes.fromhex(h))

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    __slots__ = ()
    _LEN = _JOB_LEN

    _counter_lock = threading.Lock()
    _counter = 0

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(struct.pack("<I", i))


class NodeID(BaseID):
    __slots__ = ()
    _LEN = _UNIQUE_LEN


class WorkerID(BaseID):
    __slots__ = ()
    _LEN = _UNIQUE_LEN


class PlacementGroupID(BaseID):
    __slots__ = ()
    _LEN = _UNIQUE_LEN


class ActorID(BaseID):
    __slots__ = ()
    _LEN = _ACTOR_LEN

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(_ACTOR_LEN - _JOB_LEN))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_LEN])


class TaskID(BaseID):
    __slots__ = ()
    _LEN = _TASK_LEN

    @classmethod
    def for_job(cls, job_id: JobID) -> "TaskID":
        return cls(job_id.binary() + os.urandom(_TASK_LEN - _JOB_LEN))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(
            actor_id.job_id().binary() + os.urandom(_TASK_LEN - _JOB_LEN)
        )

    def job_id(self) -> JobID:
        return JobID(self._bytes[:_JOB_LEN])


class ObjectID(BaseID):
    """TaskID + 4-byte little-endian index.

    Return values use indices 1..MAX_RETURNS; ``put`` objects use
    indices above ``MAX_RETURNS`` — the creating task (and therefore the
    owner and the lineage needed for reconstruction) is recoverable from
    the first 14 bytes.
    """

    __slots__ = ()
    _LEN = _OBJECT_LEN

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        if not 1 <= index <= MAX_RETURNS:
            raise ValueError(f"return index out of range: {index}")
        return cls(task_id.binary() + struct.pack("<I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        if not 1 <= put_index < (1 << 32) - _PUT_BASE:
            raise ValueError(f"put index out of range: {put_index}")
        return cls(task_id.binary() + struct.pack("<I", _PUT_BASE + put_index))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_TASK_LEN])

    def job_id(self) -> JobID:
        return self.task_id().job_id()

    def index(self) -> int:
        return struct.unpack("<I", self._bytes[_TASK_LEN:])[0]

    def is_return(self) -> bool:
        return self.index() <= _PUT_BASE

    def is_put(self) -> bool:
        return self.index() > _PUT_BASE
