"""Runtime-environment materialization: plugins, pip, py_modules.

Reference: `python/ray/_private/runtime_env/` — per-task/actor
environments shipped from the driver and materialized on the executing
worker, extensible through a plugin protocol
(`runtime_env/plugin.py`).  Sections supported by built-in plugins:

- ``env_vars``: plain environment variables,
- ``working_dir``: chdir + sys.path root,
- ``py_modules``: local packages zipped on the driver, stored once in
  the controller KV under their content hash (reference:
  `runtime_env/packaging.py`), extracted into a content-addressed
  cache on the worker,
- ``pip``: requirements installed into a content-addressed target
  directory (``pip install --target``) prepended to sys.path —
  the reference's pip plugin shape (`runtime_env/pip.py`) without
  per-env virtualenvs.

Custom sections: subclass :class:`RuntimeEnvPlugin` and call
:func:`register_runtime_env_plugin` — `apply_runtime_env` runs plugins
in priority order on the worker.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import zipfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

_CACHE_ROOT = os.path.join(
    os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"), "py_modules_cache"
)


def _module_root(mod: Any) -> str:
    """Filesystem root of a module object or an explicit path string."""
    if isinstance(mod, str):
        return os.path.abspath(mod)
    path = getattr(mod, "__path__", None)
    if path:  # package
        return os.path.abspath(list(path)[0])
    f = getattr(mod, "__file__", None)
    if f:
        return os.path.abspath(f)
    raise ValueError(f"cannot locate module source for {mod!r}")


def package_py_modules(mods: Sequence[Any]) -> List[Tuple[str, str, bytes]]:
    """Zip each module/path.  Returns [(import_name, kv_key, zip_bytes)]
    — kv_key is content-addressed, so identical code ships once."""
    out = []
    for mod in mods:
        root = _module_root(mod)
        name = os.path.basename(root.rstrip("/"))
        buf = io.BytesIO()

        def _add(z, full, rel):
            # fixed timestamp + sorted walk: the key must depend on
            # CONTENT only, or fresh checkouts (new mtimes) re-upload
            # byte-identical code under new keys
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            with open(full, "rb") as f:
                z.writestr(info, f.read())

        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            if os.path.isdir(root):
                for dirpath, dirnames, filenames in os.walk(root):
                    dirnames[:] = sorted(
                        d for d in dirnames if d != "__pycache__"
                    )
                    for fn in sorted(filenames):
                        if fn.endswith(".pyc"):
                            continue
                        full = os.path.join(dirpath, fn)
                        rel = os.path.join(
                            name, os.path.relpath(full, root)
                        )
                        _add(z, full, rel)
            else:
                _add(z, root, name)
        blob = buf.getvalue()
        key = "pymod:" + hashlib.sha256(blob).hexdigest()[:32]
        out.append((name, key, blob))
    return out


def py_module_cache_dir(key: str) -> str:
    """Cache location for a packaged module — derivable from the key
    alone, so workers can skip the KV fetch when already extracted."""
    return os.path.join(_CACHE_ROOT, key.split(":", 1)[1])


def module_stat_sig(root: str) -> str:
    """Cheap content signature (relpath, size, mtime_ns) — a stat walk,
    no compression — for the driver-side packaging cache."""
    h = hashlib.sha256()
    if os.path.isdir(root):
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".pyc"):
                    continue
                full = os.path.join(dirpath, fn)
                st = os.stat(full)
                h.update(
                    f"{os.path.relpath(full, root)}:{st.st_size}:"
                    f"{st.st_mtime_ns};".encode()
                )
    else:
        st = os.stat(root)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()


def runtime_env_hash(renv: Optional[Dict[str, Any]]) -> Optional[str]:
    """Stable identity of a runtime env: workers are dedicated per env
    hash (reference: worker pools keyed by runtime-env hash,
    `worker_pool.h` runtime_env_hash matching)."""
    if not renv:
        return None
    try:
        blob = json.dumps(renv, sort_keys=True, default=str)
    except TypeError:
        blob = repr(sorted(renv.items()))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ----------------------------------------------------------------------
# plugin protocol (reference: `runtime_env/plugin.py` RuntimeEnvPlugin)
# ----------------------------------------------------------------------
class RuntimeEnvPlugin:
    """One runtime-env section.  `name` is the dict key the plugin
    owns; `setup` runs on the worker BEFORE user code deserializes,
    lowest `priority` first."""

    name: str = ""
    priority: int = 10

    async def setup(self, value: Any, runtime: Any) -> None:
        raise NotImplementedError


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_runtime_env_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a non-empty name")
    _PLUGINS[plugin.name] = plugin


def unregister_runtime_env_plugin(name: str) -> None:
    _PLUGINS.pop(name, None)


class _EnvVarsPlugin(RuntimeEnvPlugin):
    name = "env_vars"
    priority = 0

    async def setup(self, value, runtime):
        os.environ.update(value or {})


class _WorkingDirPlugin(RuntimeEnvPlugin):
    name = "working_dir"
    priority = 1

    async def setup(self, value, runtime):
        if not value:
            return
        os.makedirs(value, exist_ok=True)
        os.chdir(value)
        if value not in sys.path:
            sys.path.insert(0, value)


class _PyModulesPlugin(RuntimeEnvPlugin):
    name = "py_modules"
    priority = 2

    async def setup(self, value, runtime):
        for _name, key in value or ():
            dest = py_module_cache_dir(key)
            if not os.path.isdir(dest):
                pkg_blob = await runtime.controller.call(
                    "kv_get", {"key": key}
                )
                if pkg_blob is None:
                    raise RuntimeError(
                        f"py_module package {key} missing from KV"
                    )
                dest = materialize_py_module(key, pkg_blob)
            if dest not in sys.path:
                sys.path.insert(0, dest)


def pip_cache_dir(packages: Sequence[str]) -> str:
    h = hashlib.sha256(
        ";".join(sorted(packages)).encode()
    ).hexdigest()[:32]
    return os.path.join(
        os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"), "pip_cache", h
    )


class _PipPlugin(RuntimeEnvPlugin):
    """`{"pip": [reqs...]}` or `{"pip": {"packages": [...],
    "pip_install_options": [...]}}` — installs into a content-addressed
    `--target` dir prepended to sys.path (reference shape:
    `runtime_env/pip.py`; shared site-packages instead of a venv per
    env).  Idempotent across workers via a done-marker."""

    name = "pip"
    priority = 3

    async def setup(self, value, runtime):
        if not value:
            return
        if isinstance(value, dict):
            packages = list(value.get("packages", []))
            options = list(value.get("pip_install_options", []))
        else:
            packages = list(value)
            options = []
        if not packages:
            return
        target = pip_cache_dir(packages + options)
        marker = os.path.join(target, ".rt_pip_done")
        if not os.path.exists(marker):
            import asyncio

            await asyncio.get_running_loop().run_in_executor(
                None, self._install_locked, target, marker, packages,
                options,
            )
        if target not in sys.path:
            sys.path.insert(0, target)

    @staticmethod
    def _install_locked(target, marker, packages, options):
        """Cross-process flock: workers dedicated to the same env on one
        host must not race concurrent `pip install --target` into the
        shared cache dir."""
        import fcntl

        os.makedirs(os.path.dirname(target), exist_ok=True)
        with open(target + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                if os.path.exists(marker):
                    return  # a peer installed while we waited
                cmd = [
                    sys.executable, "-m", "pip", "install",
                    "--target", target, "--no-warn-script-location",
                    *options, *packages,
                ]
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=600
                )
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip runtime_env install failed:\n{proc.stdout}\n"
                        f"{proc.stderr}"
                    )
                with open(marker, "w") as f:
                    f.write("ok")
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)


def _conda_exe() -> str:
    """Conda binary: `RT_CONDA_EXE` override (also the test seam) or
    `conda` on PATH."""
    return os.environ.get("RT_CONDA_EXE", "conda")


def conda_env_cache_dir(spec: Dict[str, Any]) -> str:
    h = hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()
    ).hexdigest()[:32]
    return os.path.join(
        os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"), "conda_cache", h
    )


def _conda_site_packages(prefix: str) -> List[str]:
    """site-packages dirs under a conda prefix (any python version)."""
    import glob

    return sorted(
        glob.glob(os.path.join(prefix, "lib", "python*", "site-packages"))
    )


class _CondaPlugin(RuntimeEnvPlugin):
    """`{"conda": "existing-env-name-or-prefix"}` or
    `{"conda": {...environment.yml dict...}}` (reference:
    `runtime_env/conda.py` CondaPlugin).

    Deliberate departure from the reference: instead of re-execing the
    worker under the env's interpreter (`conda activate` command
    prefix), the env's site-packages are prepended to sys.path of the
    shared interpreter — the same shape as the pip plugin.  Workers are
    already dedicated per env hash, so the import-path swap is safe;
    envs pinning a different python version are rejected.  Dict specs
    are materialized once per host into a content-addressed prefix
    (`conda env create -p`), guarded by a cross-process flock.
    """

    name = "conda"
    priority = 4

    async def setup(self, value, runtime):
        if not value:
            return
        import asyncio

        await asyncio.get_running_loop().run_in_executor(
            None, self._setup_sync, value
        )

    def _setup_sync(self, value):
        if isinstance(value, str):
            prefix = self._resolve_named_env(value)
        elif isinstance(value, dict):
            prefix = self._materialize(value)
        else:
            raise RuntimeError(
                "conda runtime_env must be an env name/prefix or an "
                f"environment.yml dict, got {type(value).__name__}"
            )
        sps = _conda_site_packages(prefix)
        if not sps:
            raise RuntimeError(
                f"conda env at {prefix} has no site-packages"
            )
        for sp in reversed(sps):
            if sp not in sys.path:
                sys.path.insert(0, sp)

    @staticmethod
    def _resolve_named_env(name: str) -> str:
        """Accept an env name or a full prefix path (reference:
        `conda.py:349` accepts either, validated against
        `conda info --json`)."""
        if os.path.isdir(name):
            return os.path.abspath(name)
        proc = subprocess.run(
            [_conda_exe(), "env", "list", "--json"],
            capture_output=True, text=True, timeout=120,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"`conda env list` failed:\n{proc.stderr}"
            )
        for prefix in json.loads(proc.stdout).get("envs", []):
            if os.path.basename(prefix) == name:
                return prefix
        raise RuntimeError(
            f"conda env {name!r} not found; only existing envs can be "
            "named — pass an environment.yml dict to create one"
        )

    @staticmethod
    def _materialize(spec: Dict[str, Any]) -> str:
        import fcntl
        import tempfile

        prefix = conda_env_cache_dir(spec)
        marker = os.path.join(prefix, ".rt_conda_done")
        if os.path.exists(marker):
            return prefix
        os.makedirs(os.path.dirname(prefix), exist_ok=True)
        with open(prefix + ".lock", "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                if os.path.exists(marker):
                    return prefix  # a peer created it while we waited
                with tempfile.NamedTemporaryFile(
                    "w", suffix=".yml", delete=False
                ) as f:
                    # environment.yml is YAML but every env dict we
                    # accept is also valid JSON, which YAML parses
                    json.dump(spec, f)
                    yml = f.name
                try:
                    proc = subprocess.run(
                        [_conda_exe(), "env", "create", "-p", prefix,
                         "-f", yml],
                        capture_output=True, text=True, timeout=1800,
                    )
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"conda env create failed:\n{proc.stdout}\n"
                            f"{proc.stderr}"
                        )
                except BaseException:
                    # a partial prefix would poison the cache forever:
                    # unlike pip's --target, `conda env create -p`
                    # refuses an existing directory, so every retry of
                    # this env hash would fail with "prefix exists"
                    import shutil

                    shutil.rmtree(prefix, ignore_errors=True)
                    raise
                finally:
                    os.unlink(yml)
                with open(marker, "w") as f:
                    f.write("ok")
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
        return prefix


def validate_runtime_env(renv: Optional[Dict[str, Any]]) -> None:
    """Driver-side sanity checks before the env ships (reference:
    `runtime_env/runtime_env.py:351` rejects pip+conda together)."""
    if not renv:
        return
    if renv.get("pip") and renv.get("conda"):
        raise ValueError(
            "runtime_env cannot set both 'pip' and 'conda'; put pip "
            "requirements under the conda env's dependencies instead"
        )
    from ray_tpu.core.container import container_section

    container_section(renv)  # raises on a malformed container/image_uri


class _ContainerPlugin(RuntimeEnvPlugin):
    """Worker-side arm of the container env (reference:
    `runtime_env/image_uri.py:106`): the image was entered at SPAWN
    time by the node daemon's command synthesis, so setup here only
    verifies this worker really was spawned for this env — a plain
    worker cannot enter an image from inside a running process."""

    name = "container"
    priority = 0

    async def setup(self, value, runtime):
        if not value:
            return
        expected = runtime_env_hash(
            getattr(runtime, "_applying_renv", None)
        )
        have = os.environ.get("RT_ENV_HASH")
        if expected is not None and have != expected:
            raise RuntimeError(
                "container runtime_env reached a worker that was not "
                f"spawned in its image (want env {expected}, worker "
                f"has {have!r}) — scheduler dedication bug"
            )


class _ImageUriPlugin(_ContainerPlugin):
    name = "image_uri"


for _p in (_EnvVarsPlugin(), _WorkingDirPlugin(), _PyModulesPlugin(),
           _PipPlugin(), _CondaPlugin(), _ContainerPlugin(),
           _ImageUriPlugin()):
    register_runtime_env_plugin(_p)


async def apply_runtime_env(renv: Dict[str, Any], runtime: Any) -> None:
    """Worker-side: run every known plugin over its section, lowest
    priority first.  Unknown sections without a registered plugin are
    an error — silently ignoring them would hide typos the way the
    reference explicitly refuses to."""
    if not renv:
        return
    unknown = set(renv) - set(_PLUGINS)
    if unknown:
        raise RuntimeError(
            f"runtime_env sections {sorted(unknown)} have no registered "
            "plugin (register_runtime_env_plugin)"
        )
    if runtime is not None:
        runtime._applying_renv = renv  # full env, for plugin hash checks
    try:
        for plugin in sorted(_PLUGINS.values(), key=lambda p: p.priority):
            if plugin.name in renv:
                await plugin.setup(renv[plugin.name], runtime)
    finally:
        if runtime is not None:
            runtime._applying_renv = None


def materialize_py_module(key: str, blob: bytes) -> str:
    """Extract one packaged module into the content-addressed cache and
    return the directory to put on sys.path.  Idempotent across
    processes: first extractor wins via atomic rename."""
    dest = py_module_cache_dir(key)
    if not os.path.isdir(dest):
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # peer won the race
    return dest
