"""Runtime-environment materialization helpers.

Reference: `python/ray/_private/runtime_env/` — per-actor environments
shipped from the driver and materialized on the executing worker.
Supported here: `env_vars`, `working_dir`, and `py_modules` (this
module): local packages/files are zipped on the driver, stored once in
the controller KV under their content hash (the reference uploads
packages to the GCS the same way, `runtime_env/packaging.py`), and
extracted into a content-addressed cache on the worker before the
actor's class deserializes — so by-value pickles that import the
module resolve even on hosts that never saw the driver's filesystem
layout.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile
from typing import Any, List, Sequence, Tuple

_CACHE_ROOT = os.path.join(
    os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"), "py_modules_cache"
)


def _module_root(mod: Any) -> str:
    """Filesystem root of a module object or an explicit path string."""
    if isinstance(mod, str):
        return os.path.abspath(mod)
    path = getattr(mod, "__path__", None)
    if path:  # package
        return os.path.abspath(list(path)[0])
    f = getattr(mod, "__file__", None)
    if f:
        return os.path.abspath(f)
    raise ValueError(f"cannot locate module source for {mod!r}")


def package_py_modules(mods: Sequence[Any]) -> List[Tuple[str, str, bytes]]:
    """Zip each module/path.  Returns [(import_name, kv_key, zip_bytes)]
    — kv_key is content-addressed, so identical code ships once."""
    out = []
    for mod in mods:
        root = _module_root(mod)
        name = os.path.basename(root.rstrip("/"))
        buf = io.BytesIO()

        def _add(z, full, rel):
            # fixed timestamp + sorted walk: the key must depend on
            # CONTENT only, or fresh checkouts (new mtimes) re-upload
            # byte-identical code under new keys
            info = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            with open(full, "rb") as f:
                z.writestr(info, f.read())

        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            if os.path.isdir(root):
                for dirpath, dirnames, filenames in os.walk(root):
                    dirnames[:] = sorted(
                        d for d in dirnames if d != "__pycache__"
                    )
                    for fn in sorted(filenames):
                        if fn.endswith(".pyc"):
                            continue
                        full = os.path.join(dirpath, fn)
                        rel = os.path.join(
                            name, os.path.relpath(full, root)
                        )
                        _add(z, full, rel)
            else:
                _add(z, root, name)
        blob = buf.getvalue()
        key = "pymod:" + hashlib.sha256(blob).hexdigest()[:32]
        out.append((name, key, blob))
    return out


def py_module_cache_dir(key: str) -> str:
    """Cache location for a packaged module — derivable from the key
    alone, so workers can skip the KV fetch when already extracted."""
    return os.path.join(_CACHE_ROOT, key.split(":", 1)[1])


def module_stat_sig(root: str) -> str:
    """Cheap content signature (relpath, size, mtime_ns) — a stat walk,
    no compression — for the driver-side packaging cache."""
    h = hashlib.sha256()
    if os.path.isdir(root):
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
            for fn in sorted(filenames):
                if fn.endswith(".pyc"):
                    continue
                full = os.path.join(dirpath, fn)
                st = os.stat(full)
                h.update(
                    f"{os.path.relpath(full, root)}:{st.st_size}:"
                    f"{st.st_mtime_ns};".encode()
                )
    else:
        st = os.stat(root)
        h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()


def materialize_py_module(key: str, blob: bytes) -> str:
    """Extract one packaged module into the content-addressed cache and
    return the directory to put on sys.path.  Idempotent across
    processes: first extractor wins via atomic rename."""
    dest = py_module_cache_dir(key)
    if not os.path.isdir(dest):
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        tmp = f"{dest}.tmp.{os.getpid()}"
        with zipfile.ZipFile(io.BytesIO(blob)) as z:
            z.extractall(tmp)
        try:
            os.rename(tmp, dest)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # peer won the race
    return dest
