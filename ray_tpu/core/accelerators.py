"""TPU accelerator detection, isolation, and slice gang resources.

The runtime's whole thesis is that TPU topology is first-class, so the
node daemon must know — without operator flags — how many chips it has,
what slice it belongs to, and how to hand *disjoint* chip subsets to
concurrent workers on one host.

Capability parity with the reference's accelerator manager
(`/root/reference/python/ray/_private/accelerators/tpu.py`):
- chip autodetection via /dev/accel* and /dev/vfio (ref `:102`),
- per-worker chip isolation via TPU_VISIBLE_CHIPS (+ the
  TPU_CHIPS_PER_HOST_BOUNDS / TPU_HOST_BOUNDS trio libtpu needs for
  sub-host meshes, ref `:155-196`),
- `v{gen}-{chips}` slice-type validation (ref `:120`),
- slice metadata from GKE env vars / GCE metadata (ref `:231,274`),
- the `TPU-{slice}-head` gang resource on worker 0 of a slice plus a
  per-slice name resource on every member (ref `:381`).

Unlike the reference (which only sets env vars inside an already-forked
worker), the daemon here assigns chips at *lease grant* time and pins
them to the worker process for its lifetime — two `num_tpus=1` actors on
one 8-chip host each see exactly one, different chip.
"""

from __future__ import annotations

import functools
import glob
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TPU_VALID_CHIP_COUNTS = (1, 2, 4, 8)

# env overrides (tests / operators); RT_TPU_CHIPS forces the chip count
NUM_CHIPS_ENV = "RT_TPU_CHIPS"
SLICE_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # set by GKE
TPU_NAME_ENV = "TPU_NAME"  # set by GKE / operator
WORKER_ID_ENV = "TPU_WORKER_ID"  # set by GKE

VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
_SINGLE_HOST_BOUNDS = "1,1,1"

_GCE_METADATA_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"
)

_slice_type_re = re.compile(r"^v\d+[a-zA-Z]*-\d+$")


_metadata_dead = False  # set after the first failed lookup: off-cloud


@functools.lru_cache(maxsize=None)
def _gce_metadata(key: str) -> Optional[str]:
    """GCE instance-metadata lookup; quiet None off-cloud.  Cached, and
    disabled entirely after the first failure so node startup never
    pays more than one ~1s probe outside GCP."""
    global _metadata_dead
    if _metadata_dead or os.environ.get("RT_TPU_NO_METADATA"):
        return None
    try:
        import urllib.request

        req = urllib.request.Request(
            _GCE_METADATA_URL + key, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=1.0) as resp:
            if resp.status == 200:
                return resp.read().decode().strip() or None
            return None
    except Exception as e:
        logger.debug("GCE metadata probe failed (%s); not on TPU VM", e)
        _metadata_dead = True
    return None


def detect_num_chips() -> int:
    """Count local TPU chips: RT_TPU_CHIPS override, /dev/accel*, then
    /dev/vfio numeric entries (newer TPU VMs).  VFIO entries are only
    trusted when something else says this is a TPU host (GKE env var or
    GCE metadata) — any passthrough device binds vfio, and a false
    positive here would advertise phantom TPU resources cluster-wide."""
    override = os.environ.get(NUM_CHIPS_ENV)
    if override:
        try:
            return max(0, int(override))
        except ValueError:
            logger.warning("bad %s=%r", NUM_CHIPS_ENV, override)
    n = len(glob.glob("/dev/accel*"))
    if n:
        return n
    try:
        vfio = len([e for e in os.listdir("/dev/vfio") if e.isdigit()])
    except FileNotFoundError:
        return 0
    if vfio and (os.environ.get(SLICE_TYPE_ENV) or os.environ.get(TPU_NAME_ENV)
                 or _gce_metadata("accelerator-type")):
        return vfio
    return 0


def is_valid_slice_type(slice_type: str) -> bool:
    """`v{generation}-{chips_or_cores}`, e.g. v4-16, v5e-256."""
    return bool(_slice_type_re.match(slice_type))


def get_slice_type() -> Optional[str]:
    st = os.environ.get(SLICE_TYPE_ENV) or _gce_metadata("accelerator-type")
    if st and is_valid_slice_type(st):
        return st
    return None


def get_tpu_name() -> Optional[str]:
    return os.environ.get(TPU_NAME_ENV) or _gce_metadata("instance-id")


def get_worker_id() -> Optional[int]:
    wid = os.environ.get(WORKER_ID_ENV) or _gce_metadata("agent-worker-number")
    try:
        return int(wid) if wid is not None else None
    except ValueError:
        return None


def num_hosts_in_slice(slice_type: str) -> int:
    """Hosts in a slice: v2/v3/v4 expose 8 cores per host, later gens 4
    chips per host (same arithmetic the reference applies, ref `:274`)."""
    gen, _, count = slice_type.partition("-")
    per_host = 8 if gen in ("v2", "v3", "v4") else 4
    return max(1, int(count) // per_host)


def validate_chip_request(quantity: float) -> Optional[str]:
    """Whole-chip requests must tile the host interconnect; fractional
    shares (no isolation) are allowed like fractional GPUs."""
    if quantity < 1:
        return None
    if quantity != int(quantity) or int(quantity) not in TPU_VALID_CHIP_COUNTS:
        return (
            f"num_tpus={quantity} is not a supported per-host chip count; "
            f"use one of {TPU_VALID_CHIP_COUNTS} or a fraction < 1"
        )
    return None


def node_tpu_extras(num_chips: int) -> Tuple[Dict[str, float], Dict[str, str]]:
    """(extra resources, node labels) for a node with `num_chips` chips.

    Resources: the slice-name resource on every member host (lets a
    coordinator target its own slice) and `TPU-{slice_type}-head` on
    worker 0 only — the gang-scheduling handle: one task grabs the head
    resource, discovers the slice, then fans out per-host tasks pinned
    by the name resource.
    Labels: `tpu-slice` (ICI-domain key the placement-group STRICT_PACK
    policy packs by, `core/placement.py:103`) plus type/worker-id/chips.
    """
    resources: Dict[str, float] = {}
    labels: Dict[str, str] = {}
    if num_chips <= 0:
        return resources, labels
    slice_type = get_slice_type()
    name = get_tpu_name()
    worker_id = get_worker_id()
    labels["tpu-chips"] = str(num_chips)
    if slice_type:
        labels["tpu-type"] = slice_type
        labels["accelerator-type"] = "TPU-" + slice_type.split("-")[0].upper()
    if name:
        labels["tpu-slice"] = name
        resources[name] = 1.0
    if worker_id is not None:
        labels["tpu-worker-id"] = str(worker_id)
    if slice_type and name and (worker_id == 0 or worker_id is None):
        resources[f"TPU-{slice_type}-head"] = 1.0
    return resources, labels


def chip_isolation_env(chip_ids: List[int], total_chips: int) -> Dict[str, str]:
    """Env vars that restrict a worker process to `chip_ids`.

    libtpu needs the host-bounds trio for 1- and 2-chip sub-host
    topologies; all-chip grants clear the restriction (framework
    defaults see the whole host).
    """
    if total_chips and len(chip_ids) >= total_chips:
        return {
            VISIBLE_CHIPS_ENV: "",  # sentinel: worker unsets these
            CHIPS_PER_HOST_BOUNDS_ENV: "",
            HOST_BOUNDS_ENV: "",
        }
    env = {VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chip_ids)}
    bounds = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1"}.get(len(chip_ids))
    if bounds:
        # sub-host grant: libtpu needs the physical bounds of the
        # visible subset (1=single chip, 2=1x2, 4=2x2 — the contiguous
        # blocks the sequential allocator hands out on 2x4 hosts)
        env[CHIPS_PER_HOST_BOUNDS_ENV] = bounds
        env[HOST_BOUNDS_ENV] = _SINGLE_HOST_BOUNDS
    return env


class ChipPool:
    """Daemon-side allocator mapping whole-chip leases to disjoint chip
    id sets.  Chips are pinned per worker process: once a worker has
    initialized its runtime against a chip subset, handing it a
    different subset later would be silently ignored by the framework —
    so reuse prefers workers whose pinned set already matches.
    """

    def __init__(self, num_chips: int):
        self.num_chips = num_chips
        self._free = set(range(num_chips))
        self._by_worker: Dict[str, Tuple[int, ...]] = {}

    def assign(self, worker_id: str, n: int) -> Optional[Tuple[int, ...]]:
        held = self._by_worker.get(worker_id)
        if held is not None:
            return held if len(held) == n else None
        if n > len(self._free):
            return None
        chips = tuple(sorted(self._free)[:n])
        self._free.difference_update(chips)
        self._by_worker[worker_id] = chips
        return chips

    def pinned(self, worker_id: str) -> Optional[Tuple[int, ...]]:
        return self._by_worker.get(worker_id)

    def release_worker(self, worker_id: str) -> None:
        chips = self._by_worker.pop(worker_id, None)
        if chips:
            self._free.update(chips)

    @property
    def free_count(self) -> int:
        return len(self._free)
