"""Worker process entry point.

Spawned by the node daemon (reference: `WorkerPool::StartWorkerProcess`,
`src/ray/raylet/worker_pool.h`); hosts a Runtime in worker mode whose io
loop receives execute_task pushes and runs user code in executor
threads (reference: the worker exec loop, `core_worker.cc:2908` +
`_raylet.pyx task_execution_handler:2222`).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time

import faulthandler

faulthandler.register(signal.SIGUSR1, all_threads=True)


def main():
    logging.basicConfig(
        level=os.environ.get("RT_LOG_LEVEL", "INFO"),
        format="%(asctime)s worker %(levelname)s %(message)s",
    )
    node_socket = os.environ["RT_NODE_SOCKET"]
    host, port = os.environ["RT_CONTROLLER"].rsplit(":", 1)

    from ray_tpu.core.runtime import Runtime, set_runtime

    rt = Runtime("worker")
    rt.start(node_socket, (host, int(port)),
             serve_dir=os.path.dirname(node_socket))
    set_runtime(rt)

    # exit when the node daemon goes away (socket closes) or parent dies
    ppid = os.getppid()
    try:
        while True:
            time.sleep(0.5)
            if rt.noded is None or rt.noded.closed:
                break
            if os.getppid() != ppid:
                break
    except KeyboardInterrupt:
        pass
    rt.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
