"""Worker process entry point.

Spawned by the node daemon (reference: `WorkerPool::StartWorkerProcess`,
`src/ray/raylet/worker_pool.h`); hosts a Runtime in worker mode whose io
loop receives execute_task pushes and runs user code in executor
threads (reference: the worker exec loop, `core_worker.cc:2908` +
`_raylet.pyx task_execution_handler:2222`).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time

import faulthandler

faulthandler.register(signal.SIGUSR1, all_threads=True)


def main():
    logging.basicConfig(
        level=os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s worker %(levelname)s %(message)s",
    )
    # Import parity with the driver: functions pickled BY REFERENCE
    # (module-level defs in importable modules — e.g. a pytest-imported
    # test module) must resolve here too.  Single-host clusters share
    # the filesystem, so adopting the driver's sys.path additions is
    # exact; multi-host deployments ship code via runtime_env
    # working_dir instead (reference: the driver's code_search_path /
    # runtime_env py_modules mechanism).
    extra = os.environ.get("RT_DRIVER_SYS_PATH")
    if extra:
        import json as _json

        from ray_tpu.core.env_utils import adopt_sys_path

        adopt_sys_path(_json.loads(extra))
    # test hook: simulate the slow-boot regime (heavy imports, axon
    # tunnel handshakes) that the worker pool's starting-worker
    # accounting must tolerate without a spawn storm
    boot_delay = float(os.environ.get("RT_TEST_WORKER_BOOT_DELAY", "0"))
    if boot_delay > 0:
        time.sleep(boot_delay)
    node_socket = os.environ["RT_NODE_SOCKET"]
    host, port = os.environ["RT_CONTROLLER"].rsplit(":", 1)

    from ray_tpu.core.runtime import Runtime, set_runtime

    rt = Runtime("worker")
    # publish the runtime BEFORE registering with the daemon: a task can
    # be pushed the instant registration lands, and its user code may
    # call get_runtime() immediately
    set_runtime(rt)
    # tee BEFORE registering: a task can land the instant registration
    # does, and its first prints must not bypass the stream (reference:
    # log_monitor.py tailing worker files); the tee passes through to
    # this worker's session-dir log file either way
    from ray_tpu.core.log_stream import install_worker_tee

    install_worker_tee()
    rt.start(node_socket, (host, int(port)),
             serve_dir=os.path.dirname(node_socket))

    # exit when the node daemon goes away (socket closes) or parent dies
    ppid = os.getppid()
    try:
        while True:
            time.sleep(0.5)
            if rt.noded is None or rt.noded.closed:
                break
            if os.getppid() != ppid:
                break
    except KeyboardInterrupt:
        pass
    rt.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
