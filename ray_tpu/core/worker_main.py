"""Worker process entry point.

Spawned by the node daemon (reference: `WorkerPool::StartWorkerProcess`,
`src/ray/raylet/worker_pool.h`); hosts a Runtime in worker mode whose io
loop receives execute_task pushes and runs user code in executor
threads (reference: the worker exec loop, `core_worker.cc:2908` +
`_raylet.pyx task_execution_handler:2222`).
"""

from __future__ import annotations

import logging
import os
import signal
import sys
import threading
import time

import faulthandler

faulthandler.register(signal.SIGUSR1, all_threads=True)


def install_asyncio_dump(get_loop, sig=signal.SIGUSR2):
    """`kill -USR2 <pid>` prints every pending asyncio task's coroutine
    stack to stderr — the coroutine-level sibling of the USR1 thread
    dump (thread stacks show an idle io loop even when a hundred
    coroutines are parked on never-resolving futures; this shows WHERE
    they are parked).  Safe in the handler: it only schedules the dump
    onto the loop."""
    import asyncio

    def _chain(coro):
        """Follow the await chain to its suspension point — get_stack
        alone shows only the outermost frame, which for a deep await
        chain says nothing about what is actually being waited on."""
        out = []
        hops = 0
        while coro is not None and hops < 24:
            hops += 1
            fr = (getattr(coro, "cr_frame", None)
                  or getattr(coro, "gi_frame", None))
            if fr is not None:
                out.append(f"{fr.f_code.co_name}:{fr.f_lineno}")
            coro = (getattr(coro, "cr_await", None)
                    or getattr(coro, "gi_yieldfrom", None))
        return out

    def _dump():
        tasks = [t for t in asyncio.all_tasks() if not t.done()]
        print(f"--- asyncio dump: {len(tasks)} pending tasks ---",
              file=sys.stderr, flush=True)
        for t in tasks:
            print(f"task {t.get_name()} {' -> '.join(_chain(t.get_coro()))}",
                  file=sys.stderr)
        print("--- end asyncio dump ---", file=sys.stderr, flush=True)

    def _handler(signum, frame):
        loop = get_loop()
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(_dump)

    signal.signal(sig, _handler)


def main():
    logging.basicConfig(
        level=os.environ.get("RT_LOG_LEVEL", "INFO").upper(),
        format="%(asctime)s worker %(levelname)s %(message)s",
    )
    # Import parity with the driver: functions pickled BY REFERENCE
    # (module-level defs in importable modules — e.g. a pytest-imported
    # test module) must resolve here too.  Single-host clusters share
    # the filesystem, so adopting the driver's sys.path additions is
    # exact; multi-host deployments ship code via runtime_env
    # working_dir instead (reference: the driver's code_search_path /
    # runtime_env py_modules mechanism).
    extra = os.environ.get("RT_DRIVER_SYS_PATH")
    if extra:
        import json as _json

        from ray_tpu.core.env_utils import adopt_sys_path

        adopt_sys_path(_json.loads(extra))
    # test hook: simulate the slow-boot regime (heavy imports, axon
    # tunnel handshakes) that the worker pool's starting-worker
    # accounting must tolerate without a spawn storm
    boot_delay = float(os.environ.get("RT_TEST_WORKER_BOOT_DELAY", "0"))
    if boot_delay > 0:
        time.sleep(boot_delay)
    node_socket = os.environ["RT_NODE_SOCKET"]
    host, port = os.environ["RT_CONTROLLER"].rsplit(":", 1)

    from ray_tpu.core.runtime import Runtime, set_runtime

    rt = Runtime("worker")
    # publish the runtime BEFORE registering with the daemon: a task can
    # be pushed the instant registration lands, and its user code may
    # call get_runtime() immediately
    set_runtime(rt)
    install_asyncio_dump(lambda: getattr(rt, "loop", None))
    # tee BEFORE registering: a task can land the instant registration
    # does, and its first prints must not bypass the stream (reference:
    # log_monitor.py tailing worker files); the tee passes through to
    # this worker's session-dir log file either way
    from ray_tpu.core.log_stream import install_worker_tee

    install_worker_tee()
    rt.start(node_socket, (host, int(port)),
             serve_dir=os.path.dirname(node_socket))

    # exit when the node daemon goes away (socket closes) or parent dies
    ppid = os.getppid()
    try:
        while True:
            time.sleep(0.5)
            if rt.noded is None or rt.noded.closed:
                break
            if os.getppid() != ppid:
                break
    except KeyboardInterrupt:
        pass
    rt.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
