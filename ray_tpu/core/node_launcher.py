"""Spawn a node daemon process and wait for its ready file.

Single source of truth for the noded CLI protocol — used by
`ray_tpu.init` (head auto-start), `cluster_utils.Cluster.add_node`, and
the autoscaler's `LocalNodeProvider`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.core.env_utils import infra_env


def launch_noded(
    session_dir: str,
    *,
    head: bool = False,
    controller_addr: Optional[Tuple[str, int]] = None,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    num_workers: int = 0,
    env_extra: Optional[Dict[str, str]] = None,
    # generous: a loaded single-core CI box can take >60s to fork+import
    # a daemon while a full test suite runs
    timeout: float = 150.0,
) -> Tuple[subprocess.Popen, Dict[str, Any]]:
    """Returns (process, ready-file contents)."""
    os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
    ready_file = os.path.join(session_dir, "ready.json")
    if os.path.exists(ready_file):
        os.remove(ready_file)  # reusing a session dir (head restart)
    cmd = [
        sys.executable, "-m", "ray_tpu.core.noded",
        "--session-dir", session_dir,
        "--ready-file", ready_file,
        "--num-workers", str(num_workers),
    ]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if num_tpus is not None:
        cmd += ["--num-tpus", str(num_tpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if labels:
        cmd += ["--labels", json.dumps(labels)]
    if head:
        cmd += ["--head"]
    else:
        if controller_addr is None:
            raise exc.RayTpuError("worker nodes need a controller address")
        cmd += ["--controller", f"{controller_addr[0]}:{controller_addr[1]}"]
    env = infra_env()
    if env_extra:
        env.update(env_extra)
    proc = subprocess.Popen(
        cmd,
        env=env,
        stdout=open(os.path.join(session_dir, "noded.out"), "wb"),
        stderr=subprocess.STDOUT,
    )
    deadline = time.time() + timeout
    while not os.path.exists(ready_file):
        if proc.poll() is not None:
            raise exc.RayTpuError(
                f"node daemon exited with {proc.returncode}; see "
                f"{session_dir}/noded.out"
            )
        if time.time() > deadline:
            proc.kill()
            raise exc.RayTpuError("timed out starting node daemon")
        time.sleep(0.02)
    with open(ready_file) as f:
        return proc, json.load(f)
