"""Controller: the cluster-global control plane (GCS equivalent).

Role-for-role match with the reference's `GcsServer`
(`src/ray/gcs/gcs_server/gcs_server.h:79`): node membership + health,
the actor registry with restart-on-failure (reference:
`gcs_actor_manager.h:307`), named actors, a KV store used for function
shipping and library state (reference: `gcs_kv_manager.h`), job
tracking, and placement groups (reference:
`gcs_placement_group_manager.h`).  Storage is a pluggable store —
in-memory by default, snapshot-to-disk optional — mirroring the
reference's `StoreClient` split (`store_client/in_memory_store_client.h:31`).

Runs inside the head node daemon process; remote node daemons connect
over TCP (the reference colocates GCS on the head node the same way).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import rpc
from ray_tpu.core.config import get_config
from ray_tpu.core.task_spec import ActorCreationSpec, fits as _fits, match_labels

logger = logging.getLogger(__name__)


@dataclass
class NodeInfo:
    node_id: str
    addr: Tuple[str, int]  # (host, port) of the noded server
    resources: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.time)
    is_head: bool = False
    conn: Optional[rpc.Connection] = None
    # the daemon's Prometheus /metrics port (0 = listener disabled)
    metrics_port: int = 0


def filter_by_labels(nodes, label_hard, label_soft):
    """Label constraints over node candidates (reference:
    `node_label_scheduling_policy.h:25`): `hard` filters, `soft` only
    narrows preference when at least one node satisfies it."""
    if label_hard:
        nodes = [n for n in nodes if match_labels(label_hard, n.labels)]
    if label_soft and nodes:
        preferred = [n for n in nodes
                     if match_labels(label_soft, n.labels)]
        if preferred:
            nodes = preferred
    return nodes


@dataclass
class ActorInfo:
    spec: ActorCreationSpec
    state: str = "PENDING"  # PENDING/ALIVE/RESTARTING/DEAD
    address: Optional[Tuple[str, str]] = None  # (node_id, worker_id)
    restarts_used: int = 0
    death_cause: str = ""


class Controller:
    """Service object; methods handle_<name> are RPC entry points."""

    def __init__(self, persist_path: Optional[str] = None):
        self.cfg = get_config()
        # pluggable persistence of the durable tables (reference: the
        # StoreClient seam enabling GCS fault tolerance,
        # `store_client.h` / `redis_store_client.h:106`): KV (function
        # store, job records, library state) and job registry survive a
        # head restart and rehydrate at boot (reference: GcsInitData,
        # `gcs_init_data.h`).  `persist_path` may be a bare file path
        # or a store URL (sqlite:///..., memory://) — core/storage.py.
        from ray_tpu.core.storage import store_client_for

        try:
            self._store = store_client_for(persist_path)
        except Exception as e:  # noqa: BLE001 — persistence must never
            # block boot: a bad URL/unavailable volume costs durability,
            # not the cluster
            logger.warning(
                "controller store %r unavailable (%s); running without "
                "durability", persist_path, e,
            )
            self._store = None
        self._dirty = False
        self.nodes: Dict[str, NodeInfo] = {}
        self.actors: Dict[bytes, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], bytes] = {}  # (ns, name) -> actor id
        self.kv: Dict[str, bytes] = {}
        self.jobs: Dict[str, Dict] = {}
        self.placement_groups: Dict[bytes, Any] = {}  # filled by placement module
        self._rehydrated_pgs: Dict[str, Dict] = {}  # set by load_persisted
        self.pending_demand: Dict[tuple, float] = {}  # demand sig -> last ts
        from collections import deque

        self.task_events: deque = deque(maxlen=50_000)
        # structured cluster events (reference: `src/ray/util/event.h` +
        # `dashboard/modules/event/` — lifecycle events surfaced
        # cluster-wide)
        self.cluster_events: deque = deque(maxlen=10_000)
        # unified observability plane: collected finished spans (the
        # driver-side trace collector, reference: the GCS-side task
        # events + otel export pipeline) and the latest metrics
        # snapshot per reporting process (`ray_tpu/metrics/exporter.py`)
        from ray_tpu.metrics.exporter import MetricsSink

        self.trace_spans: deque = deque(maxlen=50_000)
        self.metrics_sink = MetricsSink()
        # monotonic receipt counters: ring length alone cannot tell
        # "window full" from "window exactly filled" — the timeline's
        # truncation marker needs the real received totals
        self._spans_received = 0
        self._task_events_received = 0
        # events lost at the SOURCE (reporters' __dropped__ markers):
        # the window is incomplete even when this ring never evicted
        self._task_events_source_dropped = 0
        self._pg_manager = None  # set by placement module
        # per-bundle actor claims: (pg_id, bundle_index) ->
        # {actor_id: demand}.  The bundle-spec admission check alone
        # would let two actors oversubscribe one bundle; claims bound
        # admitted demand by what the bundle actually reserved.
        self._pg_bundle_claims: Dict[Tuple[bytes, int],
                                     Dict[bytes, Dict[str, float]]] = {}
        self._health_task: Optional[asyncio.Task] = None
        self._subscribers: Dict[str, List[rpc.Connection]] = {}

    def load_persisted(self):
        if self._store is None:
            return
        try:
            snap = self._store.load()
            if snap is None:
                return
            self.kv = dict(snap.get("kv", {}))
            self.jobs = snap.get("jobs", {})
            for job in self.jobs.values():
                # every driver of the previous incarnation is gone
                # (reference: GCS marks jobs dead for disconnected
                # drivers on restart)
                if job.get("status") == "RUNNING":
                    job["status"] = "DEAD"
            # placement groups rehydrate once the PG manager attaches
            # (reference: GcsInitData placement-group table); bundle
            # reservations re-apply as their nodes re-register
            self._rehydrated_pgs = snap.get("pgs", {})
            logger.info(
                "controller rehydrated %d kv keys, %d jobs, %d pgs via %s",
                len(self.kv), len(self.jobs),
                len(self._rehydrated_pgs),
                type(self._store).__name__,
            )
        except Exception as e:  # noqa: BLE001 — rehydration is
            # best-effort; a corrupt store must not block boot
            logger.warning("controller state rehydration failed: %s", e)

    def _mark_dirty(self):
        self._dirty = True

    def flush_snapshot(self) -> bool:
        """Synchronous snapshot write; clears dirty only on success so
        failed writes retry on the next tick.  Called by the loop and at
        daemon shutdown (the last debounce window must not be lost)."""
        if self._store is None:
            return False
        try:
            kv = {}
            for k, v in self.kv.items():
                if not isinstance(v, (bytes, bytearray)):
                    import cloudpickle

                    v = cloudpickle.dumps(v)  # kv contract is bytes, but
                    # the store must never be the thing that breaks
                kv[k] = bytes(v)
            pgs = {}
            for pid, info in self.placement_groups.items():
                if getattr(info, "state", None) == "REMOVED":
                    continue
                pgs[pid.hex()] = {
                    "bundles": [dict(b) for b in info.bundles],
                    "strategy": info.strategy,
                    "state": info.state,
                    "bundle_nodes": list(info.bundle_nodes),
                    "name": info.name,
                }
            self._store.save(
                {"kv": kv, "jobs": self.jobs, "pgs": pgs,
                 "ts": time.time()}
            )
            self._dirty = False
            return True
        except Exception as e:  # noqa: BLE001 — persistence must never
            # kill the loop; the state stays dirty and retries
            logger.warning("controller persistence failed: %s", e)
            return False

    async def _persist_loop(self):
        """Debounced snapshot writer (write-through would tax the
        function-store fast path)."""
        while True:
            await asyncio.sleep(1.0)
            if self._dirty:
                self.flush_snapshot()

    def start_health_checks(self):
        if self._store is not None:
            # hold the reference: the loop keeps only weak refs to tasks
            self._persist_task = asyncio.ensure_future(self._persist_loop())
        self._health_task = asyncio.ensure_future(self._health_loop())

    async def _health_loop(self):
        """Active node health checking (reference:
        `gcs_health_check_manager.h:39`)."""
        period = self.cfg.health_check_period_ms / 1000.0
        threshold = self.cfg.health_check_failure_threshold
        misses: Dict[str, int] = {}
        while True:
            await asyncio.sleep(period)
            for node in list(self.nodes.values()):
                if not node.alive or node.is_head or node.conn is None:
                    continue
                try:
                    await node.conn.call("ping", None, timeout=period * threshold)
                    misses[node.node_id] = 0
                except Exception as e:
                    logger.debug(
                        "health ping to %s missed (%s)", node.node_id[:8], e
                    )
                    misses[node.node_id] = misses.get(node.node_id, 0) + 1
                    if misses[node.node_id] >= threshold:
                        await self._mark_node_dead(node, "health check failed")

    def _record_event(self, event_type: str, message: str,
                      severity: str = "INFO", **custom_fields):
        from ray_tpu.util.events import make_event

        ev = make_event(
            event_type, message, severity=severity, source="controller",
            **custom_fields,
        )
        self.cluster_events.append(ev)
        # live stream to subscribers (reference: GCS event pubsub
        # channels feeding `ray events`/dashboard watchers)
        self._publish("cluster_events", ev)

    async def _mark_node_dead(self, node: NodeInfo, reason: str):
        if not node.alive:
            return
        if self.nodes.get(node.node_id) is not node:
            # a newer registration superseded this NodeInfo (daemon
            # reconnect): the stale connection's close must not kill
            # the live node or fail over its actors
            return
        logger.warning("node %s dead: %s", node.node_id, reason)
        node.alive = False
        self._record_event(
            "NODE_DEAD", f"node {node.node_id[:8]} dead: {reason}",
            severity="WARNING", node_id=node.node_id, reason=reason,
        )
        self._publish("node_dead", {"node_id": node.node_id, "reason": reason})
        # restart or bury actors that lived there
        for info in list(self.actors.values()):
            if info.address and info.address[0] == node.node_id and info.state == "ALIVE":
                await self._handle_actor_failure(info, f"node died: {reason}")

    # ---- pubsub (reference: src/ray/pubsub/) -------------------------
    def _publish(self, channel: str, msg):
        for conn in self._subscribers.get(channel, []):
            if not conn.closed:
                try:
                    conn.send("publish", {"channel": channel, "msg": msg})
                except Exception as e:
                    logger.debug(
                        "publish to %s subscriber dropped: %s", channel, e
                    )

    async def handle_subscribe(self, payload, conn):
        subs = self._subscribers.setdefault(payload["channel"], [])
        if conn not in subs:  # idempotent: re-subscribes never duplicate
            subs.append(conn)
        # closed connections would otherwise accumulate forever
        subs[:] = [c for c in subs if not c.closed]
        return {"ok": True}

    async def handle_unsubscribe(self, payload, conn):
        subs = self._subscribers.get(payload["channel"], [])
        if conn in subs:
            subs.remove(conn)
        return {"ok": True}

    async def handle_publish(self, payload, conn):
        """Generic pubsub publish: any process fans a message out to a
        channel's subscribers (reference: `src/ray/pubsub/` — e.g. the
        serve controller pushes routing-table change notifications so
        routers don't poll)."""
        self._publish(payload["channel"], payload.get("msg"))
        return {"ok": True}

    # ---- nodes -------------------------------------------------------
    async def handle_register_node(self, payload, conn):
        node = NodeInfo(
            node_id=payload["node_id"],
            addr=tuple(payload["addr"]),
            resources=payload["resources"],
            labels=payload.get("labels", {}),
            is_head=payload.get("is_head", False),
            conn=conn,
            metrics_port=int(payload.get("metrics_port", 0) or 0),
        )
        self.nodes[node.node_id] = node
        if conn is not None:
            conn.on_close = lambda c, n=node: asyncio.ensure_future(
                self._mark_node_dead(n, "connection lost")
            )
        # re-apply CREATED placement-group reservations charged to this
        # node: registration always reports FULL capacity, so both a
        # daemon reconnect and a controller-restart re-registration
        # would otherwise forget the bundles (reference: raylets restore
        # PG bundle resources on GCS restart)
        for info in self.placement_groups.values():
            if getattr(info, "state", None) != "CREATED":
                continue
            for idx, nid in enumerate(info.bundle_nodes):
                if nid == node.node_id:
                    for k, v in info.bundles[idx].items():
                        node.resources[k] = node.resources.get(k, 0.0) - v
        self._publish("node_added", {"node_id": node.node_id})
        self._record_event(
            "NODE_ADDED", f"node {node.node_id[:8]} joined",
            node_id=node.node_id, resources=dict(node.resources),
        )
        logger.info("node registered: %s resources=%s", node.node_id, node.resources)
        if self._pg_manager is not None:
            self._pg_manager.retry_pending()
        return {"ok": True}

    async def handle_get_nodes(self, payload, conn):
        return [
            {
                "node_id": n.node_id,
                "addr": n.addr,
                "resources": n.resources,
                "labels": n.labels,
                "alive": n.alive,
                "is_head": n.is_head,
                "metrics_port": n.metrics_port,
            }
            for n in self.nodes.values()
        ]

    async def handle_get_node_addr(self, payload, conn):
        n = self.nodes.get(payload["node_id"])
        return n.addr if n else None

    # ---- kv ----------------------------------------------------------
    async def handle_kv_put(self, payload, conn):
        self.kv[payload["key"]] = payload["value"]
        self._mark_dirty()
        return {"ok": True}

    # fire-and-forget variant used on the submission fast path
    handle_kv_put_oneway = handle_kv_put

    async def handle_kv_get(self, payload, conn):
        return self.kv.get(payload["key"])

    async def handle_kv_exists(self, payload, conn):
        return payload["key"] in self.kv

    async def handle_kv_del(self, payload, conn):
        self.kv.pop(payload["key"], None)
        self._mark_dirty()
        return {"ok": True}

    async def handle_kv_keys(self, payload, conn):
        prefix = payload.get("prefix", "")
        return [k for k in self.kv if k.startswith(prefix)]

    # ---- actors (reference: gcs_actor_manager.h) ---------------------
    async def handle_create_actor(self, spec: ActorCreationSpec, conn):
        if spec.name is not None:
            key = (spec.namespace, spec.name)
            if key in self.named_actors:
                existing = self.actors[self.named_actors[key]]
                if existing.state != "DEAD":
                    return {
                        "ok": False,
                        "error": f"actor name {spec.name!r} already taken",
                    }
            self.named_actors[key] = spec.actor_id.binary()
        info = ActorInfo(spec=spec)
        self.actors[spec.actor_id.binary()] = info
        ok, addr_or_err = await self._place_actor(info)
        if not ok:
            info.state = "DEAD"
            info.death_cause = addr_or_err
            return {"ok": False, "error": addr_or_err}
        info.state = "ALIVE"
        info.address = addr_or_err
        return {"ok": True, "address": info.address}

    async def _place_actor(self, info: ActorInfo):
        """Pick a node with room and ask its daemon to host the actor
        (reference: `gcs_actor_scheduler.h` leasing a worker)."""
        demand = info.spec.resources.as_dict()
        strategy = info.spec.strategy

        def _candidates() -> List[NodeInfo]:
            out = [n for n in self.nodes.values() if n.alive]
            if strategy.kind == "node_affinity" and strategy.node_id:
                out = [n for n in out if n.node_id == strategy.node_id]
            if strategy.kind == "node_labels":
                out = filter_by_labels(
                    out, strategy.label_hard, strategy.label_soft
                )
            if (self._pg_manager is not None
                    and strategy.kind == "placement_group"):
                node_id = self._pg_manager.node_for_bundle(
                    strategy.pg_id, strategy.pg_bundle_index
                )
                out = [n for n in out if n.node_id == node_id]
            return out

        # weakest-fit: most available first (spread actors)
        def avail(n: NodeInfo):
            return sum(n.resources.values())

        # NOTE: failures return immediately (no in-place retry): callers
        # like the tune controller and serve reconciler hold their own
        # event loops while awaiting this, and resources only free when
        # those loops get to reap finished actors — blocking here would
        # deadlock exactly the churn it tried to ride out.  Transient
        # failures ("resources no longer available", "no idle worker")
        # are retried by the callers.
        # a placement-group actor consumes capacity the PG ALREADY
        # reserved on its bundle's node (node.resources was decremented
        # at reservation time) — checking the demand against the
        # remaining pool would double-charge it and starve actors on
        # exactly-sized nodes (an elastic train gang on 1-CPU hosts).
        # The demand is validated against the bundle spec MINUS live
        # claims instead, so concurrent actors cannot oversubscribe
        # one bundle either.
        pg_bundle = None
        pg_claim_key = None
        aid = info.spec.actor_id.binary()
        if (self._pg_manager is not None
                and strategy.kind == "placement_group"):
            pg_info = self._pg_manager.groups.get(strategy.pg_id)
            if pg_info is not None and pg_info.bundles:
                idx = strategy.pg_bundle_index
                idx = idx if idx >= 0 else 0
                pg_bundle = pg_info.bundles[idx]
                pg_claim_key = (strategy.pg_id, idx)

        errors = []
        for node in sorted(_candidates(), key=avail, reverse=True):
            if pg_bundle is not None:
                free = dict(pg_bundle)
                for claimant, d in self._pg_bundle_claims.get(
                    pg_claim_key, {}
                ).items():
                    if claimant == aid:
                        continue  # re-placement reclaims its own slot
                    for k, v in d.items():
                        free[k] = free.get(k, 0.0) - v
                if not _fits(demand, free):
                    errors.append(
                        f"{node.node_id[:8]}: demand {demand} exceeds "
                        f"free capacity {free} of placement-group "
                        f"bundle {pg_bundle}"
                    )
                    continue
            elif not _fits(demand, node.resources):
                errors.append(f"{node.node_id[:8]}: infeasible {demand}")
                continue
            try:
                # must outlive the daemon's whole hosting window (240s
                # idle-worker wait + 300s create_actor_instance — slow
                # inits are real: first jax/TPU init in a fresh worker
                # takes tens of seconds)
                reply = await node.conn.call("host_actor", info.spec,
                                             timeout=560)
            except Exception as e:
                logger.warning("host_actor on %s failed: %s",
                               node.node_id, e)
                errors.append(f"{node.node_id[:8]}: {e}")
                continue
            if reply.get("ok"):
                if pg_claim_key is not None:
                    self._pg_bundle_claims.setdefault(
                        pg_claim_key, {}
                    )[aid] = dict(demand)
                return True, (node.node_id, reply["worker_id"])
            errors.append(f"{node.node_id[:8]}: {reply.get('error')}")
        detail = "; ".join(errors) if errors else "no alive candidate nodes"
        return False, f"no node can host actor: {detail}"

    def _release_pg_claim(self, info: "ActorInfo") -> None:
        """Free a dead actor's bundle claim so the bundle's capacity is
        admissible again (restart re-claims through _place_actor)."""
        strategy = info.spec.strategy
        if getattr(strategy, "kind", None) != "placement_group":
            return
        idx = strategy.pg_bundle_index
        key = (strategy.pg_id, idx if idx >= 0 else 0)
        claims = self._pg_bundle_claims.get(key)
        if claims is not None:
            claims.pop(info.spec.actor_id.binary(), None)
            if not claims:
                self._pg_bundle_claims.pop(key, None)

    async def handle_readopt_actor(self, payload, conn):
        """A (re)connecting daemon reports an actor it already hosts;
        rebuild the registry entry + named lookup so a restarted
        controller heals without restarting user state (reference: GCS
        restart re-binds live actors from GcsInitData +
        raylet re-registration, `gcs_actor_manager.h`)."""
        spec: ActorCreationSpec = payload["spec"]
        aid = spec.actor_id.binary()
        addr = (payload["node_id"], payload["worker_id"])
        if spec.name:
            holder = self.named_actors.get((spec.namespace, spec.name))
            if holder is not None and holder != aid:
                # the name was re-claimed by a NEW actor created after
                # the controller restarted: the old copy must not steal
                # it back — two live actors under one name
                self._record_event(
                    "ACTOR_READOPT_REJECTED",
                    f"actor {spec.actor_id.hex()[:8]} readopt rejected "
                    f"(name {spec.name!r} held by a newer actor)",
                    severity="WARNING", actor_id=spec.actor_id.hex(),
                )
                return {"ok": False, "action": "kill"}
        info = self.actors.get(aid)
        if info is not None and (
            info.state in ("RESTARTING", "DEAD")
            or (info.address is not None and tuple(info.address) != addr)
        ):
            # the controller already failed this actor over (transient
            # connection drop -> _mark_node_dead -> restart elsewhere):
            # accepting the re-adoption would leave TWO live copies.
            # The stale copy must die instead.
            self._record_event(
                "ACTOR_READOPT_REJECTED",
                f"actor {spec.actor_id.hex()[:8]} readopt rejected "
                f"(state={info.state})",
                severity="WARNING", actor_id=spec.actor_id.hex(),
            )
            return {"ok": False, "action": "kill"}
        if info is None:
            info = ActorInfo(spec=spec)
            self.actors[aid] = info
        info.state = "ALIVE"
        info.address = addr
        if spec.name:
            self.named_actors[(spec.namespace, spec.name)] = aid
        # a restarted controller has an empty claims map: re-record the
        # readopted actor's bundle claim or its bundle would admit a
        # second actor into already-occupied capacity
        strategy = getattr(spec, "strategy", None)
        if getattr(strategy, "kind", None) == "placement_group":
            idx = strategy.pg_bundle_index
            self._pg_bundle_claims.setdefault(
                (strategy.pg_id, idx if idx >= 0 else 0), {}
            )[aid] = spec.resources.as_dict()
        self._record_event(
            "ACTOR_READOPTED",
            f"actor {spec.actor_id.hex()[:8]} re-adopted from node "
            f"{payload['node_id'][:8]}",
            actor_id=spec.actor_id.hex(), node_id=payload["node_id"],
        )
        return {"ok": True}

    async def _handle_actor_failure(self, info: ActorInfo, cause: str):
        """Restart policy (reference: gcs_actor_manager.h:274 restart on
        worker/node death up to max_restarts)."""
        self._record_event(
            "ACTOR_FAILED",
            f"actor {info.spec.actor_id.hex()[:8]} failed: {cause}",
            severity="WARNING", actor_id=info.spec.actor_id.hex(),
            cause=cause,
            will_restart=info.restarts_used < info.spec.max_restarts,
        )
        if info.restarts_used < info.spec.max_restarts:
            info.restarts_used += 1
            info.state = "RESTARTING"
            self._publish(
                "actor_state",
                {"actor_id": info.spec.actor_id.binary(), "state": "RESTARTING"},
            )
            ok, addr_or_err = await self._place_actor(info)
            if ok:
                info.state = "ALIVE"
                info.address = addr_or_err
                self._publish(
                    "actor_state",
                    {
                        "actor_id": info.spec.actor_id.binary(),
                        "state": "ALIVE",
                        "address": addr_or_err,
                    },
                )
                return
            cause = addr_or_err
        info.state = "DEAD"
        info.death_cause = cause
        self._release_pg_claim(info)
        self._publish(
            "actor_state",
            {"actor_id": info.spec.actor_id.binary(), "state": "DEAD", "cause": cause},
        )

    async def handle_actor_worker_died(self, payload, conn):
        info = self.actors.get(payload["actor_id"])
        if info and info.state == "ALIVE":
            # only the node CURRENTLY hosting the actor may report its
            # death: a reconnecting daemon killing a stale superseded
            # copy (readopt rejected) must not fail over the healthy
            # replacement running elsewhere
            reporter = payload.get("node_id")
            if (
                reporter is not None
                and info.address is not None
                and info.address[0] != reporter
            ):
                return {"ok": True, "ignored": "stale host"}
            await self._handle_actor_failure(info, payload.get("cause", "worker died"))
        return {"ok": True}

    async def handle_get_actor(self, payload, conn):
        aid = payload.get("actor_id")
        if aid is None:
            key = (payload.get("namespace", "default"), payload["name"])
            aid = self.named_actors.get(key)
            if aid is None:
                return None
        info = self.actors.get(aid)
        if info is None:
            return None
        return {
            "actor_id": aid,
            "state": info.state,
            "address": info.address,
            "class_blob": info.spec.class_blob,
            "max_task_retries": info.spec.max_task_retries,
            "streaming_methods": tuple(
                getattr(info.spec, "streaming_methods", ()) or ()
            ),
            "method_groups": dict(
                getattr(info.spec, "method_groups", None) or {}
            ),
            "death_cause": info.death_cause,
        }

    async def handle_kill_actor(self, payload, conn):
        info = self.actors.get(payload["actor_id"])
        if info is None:
            return {"ok": False, "error": "no such actor"}
        no_restart = payload.get("no_restart", True)
        if no_restart:
            info.spec.max_restarts = 0
        if info.address:
            node = self.nodes.get(info.address[0])
            if node and node.conn:
                await node.conn.call(
                    "kill_worker", {"worker_id": info.address[1]}, timeout=10
                )
        if no_restart:
            # mark dead now; worker-death notifications see max_restarts=0
            info.state = "DEAD"
            info.death_cause = "killed via kill_actor"
            self._release_pg_claim(info)
            for key, aid in list(self.named_actors.items()):
                if aid == payload["actor_id"]:
                    del self.named_actors[key]
        # with no_restart=False the death notification path restarts it
        return {"ok": True}

    async def handle_list_actors(self, payload, conn):
        return [
            {
                "actor_id": aid.hex() if isinstance(aid, bytes) else aid,
                "state": i.state,
                "name": i.spec.name,
                "address": i.address,
                "restarts": i.restarts_used,
            }
            for aid, i in self.actors.items()
        ]

    # ---- placement groups -------------------------------------------
    async def handle_create_placement_group(self, payload, conn):
        info = await self._pg_manager.create(
            payload["pg_id"],
            payload["bundles"],
            payload["strategy"],
            payload.get("name", ""),
        )
        return {"ok": info.state == "CREATED", "state": info.state}

    async def handle_pg_wait_ready(self, payload, conn):
        info = self._pg_manager.groups.get(payload["pg_id"])
        if info is None:
            return {"ok": False, "error": "no such placement group"}
        timeout = payload.get("timeout")
        try:
            await asyncio.wait_for(info.ready_event.wait(), timeout)
        except asyncio.TimeoutError:
            return {"ok": False, "state": info.state}
        return {"ok": True, "state": info.state, "bundle_nodes": info.bundle_nodes}

    async def handle_pg_node_for_bundle(self, payload, conn):
        return self._pg_manager.node_for_bundle(
            payload["pg_id"], payload.get("bundle_index", -1)
        )

    async def handle_remove_placement_group(self, payload, conn):
        self._pg_manager.remove(payload["pg_id"])
        self._pg_bundle_claims = {
            k: v for k, v in self._pg_bundle_claims.items()
            if k[0] != payload["pg_id"]
        }
        return {"ok": True}

    async def handle_list_placement_groups(self, payload, conn):
        return [
            {
                "pg_id": pid.hex(),
                "state": i.state,
                "strategy": i.strategy,
                "bundles": i.bundles,
                "bundle_nodes": i.bundle_nodes,
                "name": i.name,
            }
            for pid, i in self._pg_manager.groups.items()
        ]

    # ---- jobs --------------------------------------------------------
    async def handle_report_task_events(self, payload, conn):
        """Bounded ring of task state transitions (reference:
        `gcs_task_manager.h` — the state API / timeline data source)."""
        events = payload.get("events", [])
        self._task_events_received += len(events)
        for ev in events:
            if ev.get("name") == "__dropped__":
                # a reporter's TaskEventBuffer overflowed before the
                # flush: the window is incomplete at the SOURCE, which
                # the timeline's truncation flag must reflect too
                self._task_events_source_dropped += int(
                    ev.get("count", 0) or 0)
            self.task_events.append(ev)
        return {"ok": True}

    async def handle_report_obs(self, payload, conn):
        """One batched observability frame from one process: its
        metrics-registry snapshot and/or its finished spans since the
        last flush (`core/runtime.py` flush loop, `core/noded.py` obs
        loop).  Spans are stamped with the reporter's identity here —
        the timeline's process lanes — so producers stay dumb."""
        payload = payload or {}
        node_id = str(payload.get("node_id", ""))
        kind = str(payload.get("kind", "?"))
        pid = int(payload.get("pid", 0))
        if payload.get("metrics"):
            self.metrics_sink.ingest({
                "node_id": node_id, "kind": kind, "pid": pid,
                "metrics": payload["metrics"],
            })
        spans = payload.get("spans") or []
        node8 = node_id[:8]
        proc = f"{kind}:{pid}"
        for s in spans:
            if not isinstance(s, dict):
                continue  # a malformed reporter must not poison the ring
            s.setdefault("node", node8)
            s.setdefault("proc", proc)
            self.trace_spans.append(s)
            self._spans_received += 1
        return {"ok": True}

    async def handle_cluster_metrics(self, payload, conn):
        """Merged metric snapshots from every live reporter, each
        sample tagged with its origin — the data behind the dashboard
        head's cluster-wide `/metrics` exposition."""
        return {
            "metrics": self.metrics_sink.merged(),
            "reporters": self.metrics_sink.reporter_count(),
        }

    async def handle_list_trace_spans(self, payload, conn):
        payload = payload or {}
        trace_id = payload.get("trace_id")
        limit = payload.get("limit", 10_000)
        out = []
        for s in reversed(self.trace_spans):
            if trace_id and s.get("trace_id") != trace_id:
                continue
            out.append(s)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    async def handle_timeline_data(self, payload, conn):
        """Everything the whole-run timeline needs in ONE RPC: the task
        event window, the collected span window, and HONEST truncation
        flags (ring eviction or limit clipping — the old endpoint
        silently capped at 50k with no signal)."""
        payload = payload or {}
        limit_events = int(payload.get("limit_events", 50_000))
        limit_spans = int(payload.get("limit_spans", 50_000))
        trace_id = payload.get("trace_id")
        events = list(self.task_events)
        spans = [
            s for s in self.trace_spans
            if not trace_id or s.get("trace_id") == trace_id
        ]
        events_truncated = (
            self._task_events_received > len(self.task_events)
            or len(events) > limit_events
            or self._task_events_source_dropped > 0
        )
        spans_truncated = (
            self._spans_received > len(self.trace_spans)
            or len(spans) > limit_spans
        )
        return {
            # guard the zero case: list[-0:] is the WHOLE list
            "events": events[-limit_events:] if limit_events > 0 else [],
            "spans": spans[-limit_spans:] if limit_spans > 0 else [],
            "events_truncated": events_truncated,
            "spans_truncated": spans_truncated,
        }

    async def handle_task_state_summary(self, payload, conn):
        """state -> count over the event window, reduced IN the
        controller (latest event per task wins; terminal states break
        timestamp ties).  The dashboard header polls this every couple
        of seconds — shipping the 50k-event ring over RPC per poll
        would dwarf the reduction itself, so a short TTL cache bounds
        the cost to O(ring)/TTL regardless of client count."""
        import time as _t

        now = _t.monotonic()
        cached = getattr(self, "_task_summary_cache", None)
        if cached is not None and now - cached[0] < 2.0:
            return cached[1]
        rank = {"SUBMITTED": 0, "RUNNING": 1, "FINISHED": 2, "FAILED": 2}
        latest = {}
        for ev in self.task_events:
            tid = ev.get("task_id")
            st = ev.get("state")
            if not tid or st is None:
                continue  # malformed reports must not poison the poll
            key = (ev.get("ts", 0.0), rank.get(st, 0))
            if tid not in latest or key >= latest[tid][0]:
                latest[tid] = (key, st)
        summary: dict = {}
        for _, st in latest.values():
            summary[st] = summary.get(st, 0) + 1
        self._task_summary_cache = (now, summary)
        return summary

    async def handle_list_task_events(self, payload, conn):
        payload = payload or {}
        limit = payload.get("limit", 1000)
        name = payload.get("name")
        state = payload.get("state")
        out = []
        for ev in reversed(self.task_events):
            if name and ev.get("name") != name:
                continue
            if state and ev.get("state") != state:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    # ---- structured cluster events (reference: `src/ray/util/event.h`,
    # `dashboard/modules/event/`) --------------------------------------
    async def handle_report_cluster_event(self, payload, conn):
        self.cluster_events.append(payload["event"])
        self._publish("cluster_events", payload["event"])
        return {"ok": True}

    async def handle_list_cluster_events(self, payload, conn):
        payload = payload or {}
        severity = payload.get("severity")
        event_type = payload.get("event_type")
        limit = payload.get("limit", 200)
        out = []
        for ev in reversed(self.cluster_events):
            if severity and ev.get("severity") != severity:
                continue
            if event_type and ev.get("event_type") != event_type:
                continue
            out.append(ev)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    async def handle_report_pending_demand(self, payload, conn):
        """Demand ledger for the autoscaler (reference:
        `gcs_autoscaler_state_manager.h` pending resource demand)."""
        sig = tuple(sorted(payload["resources"].items()))
        import time as _t

        self.pending_demand[sig] = _t.time()
        return {"ok": True}

    _LOAD_FIELDS = ("used", "busy", "queued", "workers", "host")

    async def handle_report_node_load(self, payload, conn):
        """Versioned delta sync of per-node load (reference:
        `ray_syncer.h:88` — nodes broadcast deltas against a shared
        version; periodic full snapshots heal any divergence).

        Payload forms:
        - `{"v": n, "full": {...}}`        — full snapshot, always applied
        - `{"v": n, "base": m, "delta": {...}}` — applied only when the
          stored version == m; otherwise dropped (a later full heals)
        - `{"v": n}`                        — heartbeat: nothing changed,
          refresh the staleness clock only
        - legacy flat payload (no "v")      — treated as a full snapshot
        """
        n = self.nodes.get(payload["node_id"])
        if n is None:
            return {"ok": True}
        import time as _t

        now = _t.time()
        load = getattr(n, "load", None)
        if "v" not in payload:  # legacy flat full report
            n.load = {
                **{f: payload.get(f) for f in self._LOAD_FIELDS},
                "used": payload.get("used", {}),
                "busy": payload.get("busy", False),
                "queued": payload.get("queued", 0),
                "ts": now,
                "v": 0,
            }
            return {"ok": True}
        v = payload["v"]
        if "full" in payload:
            n.load = {**payload["full"], "ts": now, "v": v}
        elif "delta" in payload:
            if load is not None and load.get("v") == payload.get("base"):
                load.update(payload["delta"])
                load["ts"] = now
                load["v"] = v
            # else: divergent base — drop; the sender's periodic full
            # snapshot resynchronizes within a few ticks
        else:  # heartbeat
            if load is not None and load.get("v") == v:
                load["ts"] = now
        return {"ok": True}

    async def handle_get_worker_snapshot(self, payload, conn):
        """Cluster-wide worker inventory from the per-node reporter
        cache: one call instead of an RPC per node (reference: the
        dashboard state aggregator fed by per-node reporter agents)."""
        import time as _t

        now = _t.time()
        out = []
        for n in self.nodes.values():
            if not n.alive:
                continue
            load = getattr(n, "load", None) or {}
            workers = load.get("workers")
            if workers is None or now - load.get("ts", 0) > 10.0:
                return None  # stale/missing: caller falls back to fan-out
            out.extend(workers)
        return out

    async def handle_get_autoscaler_state(self, payload, conn):
        import time as _t

        now = _t.time()
        fresh = {
            sig: ts
            for sig, ts in self.pending_demand.items()
            if now - ts < 5.0
        }
        self.pending_demand = fresh
        # gang demand: PENDING placement groups whose bundles no current
        # node set can host (reference: `gcs_autoscaler_state_manager.h`
        # reports pending PG demand so the autoscaler can provision a
        # whole slice as one unit)
        pending_gangs = [
            {
                "pg_id": pid.hex() if hasattr(pid, "hex") else str(pid),
                "bundles": [dict(b) for b in info.bundles],
                "strategy": info.strategy,
            }
            for pid, info in self.placement_groups.items()
            if getattr(info, "state", None) == "PENDING"
        ]
        return {
            "pending_demands": [dict(sig) for sig in fresh],
            "pending_gangs": pending_gangs,
            "nodes": [
                {
                    "node_id": n.node_id,
                    "resources": n.resources,
                    "alive": n.alive,
                    "is_head": n.is_head,
                    "labels": dict(getattr(n, "labels", {}) or {}),
                    "busy": bool(
                        getattr(n, "load", None)
                        and n.load.get("busy")
                        and now - n.load.get("ts", 0) < 5.0
                    ),
                }
                for n in self.nodes.values()
            ],
        }

    async def handle_register_job(self, payload, conn):
        self.jobs[payload["job_id"]] = {
            "start_time": time.time(),
            "driver_pid": payload.get("pid"),
            "status": "RUNNING",
        }
        self._mark_dirty()
        return {"ok": True}

    async def handle_list_jobs(self, payload, conn):
        return [
            {"job_id": jid, **info} for jid, info in self.jobs.items()
        ]

    # ---- spillback target query (used by noded schedulers) ----------
    def _node_utilization(self, n) -> float:
        """Dominant-resource utilization: the max per-resource ratio.
        Summing incommensurable units (CPU + TPU + byte-sized customs)
        would let one large-magnitude resource mask saturation of the
        others."""
        load = getattr(n, "load", None) or {}
        used = load.get("used") or {}
        ratios = [
            used.get(k, 0.0) / v
            for k, v in n.resources.items()
            if v > 0
        ]
        return min(1.0, max(ratios, default=0.0))

    async def handle_find_node_for(self, payload, conn):
        """Cluster-level placement for spilled-back leases (reference:
        `cluster_task_manager.cc:44` spillback), using the HYBRID
        pack-then-spread policy (`hybrid_scheduling_policy.h:50`):
        while nodes sit below the utilization threshold, pack onto the
        most-utilized such node (consolidates work, lets idle nodes
        scale down); past the threshold, spread to the least-utilized.
        Ties take a random pick among the top-k candidates so
        concurrent placements don't herd onto one node.  With
        spread=True, feasible nodes are taken round-robin
        (`spread_scheduling_policy.h:27`)."""
        import random

        from ray_tpu.core.config import get_config

        demand = payload["resources"]
        exclude = set(payload.get("exclude", []))
        feasible = [
            n for n in self.nodes.values()
            if n.alive and n.node_id not in exclude
            and _fits(demand, n.resources)
        ]
        feasible = filter_by_labels(
            feasible, payload.get("label_hard"), payload.get("label_soft")
        )
        if not feasible:
            return None
        if payload.get("spread"):
            feasible.sort(key=lambda n: n.node_id)
            self._spread_rr = getattr(self, "_spread_rr", 0) + 1
            return feasible[self._spread_rr % len(feasible)].node_id
        cfg = get_config()
        threshold = cfg.scheduler_spread_threshold

        def fits_free(n) -> bool:
            load = getattr(n, "load", None) or {}
            used = load.get("used") or {}
            free = {k: v - used.get(k, 0.0) for k, v in n.resources.items()}
            return _fits(demand, free)

        # prefer nodes whose FREE capacity can run the task now; only
        # when none exists fall back to total-feasible (work drains)
        ready = [n for n in feasible if fits_free(n)] or feasible
        below = [n for n in ready
                 if self._node_utilization(n) < threshold]
        if below:
            # pack: most-utilized below-threshold first
            below.sort(key=self._node_utilization, reverse=True)
            k = max(1, int(len(below) * cfg.scheduler_top_k_fraction))
            return random.choice(below[:k]).node_id
        # all hot: spread to the least utilized
        ready.sort(key=self._node_utilization)
        k = max(1, int(len(ready) * cfg.scheduler_top_k_fraction))
        return random.choice(ready[:k]).node_id

