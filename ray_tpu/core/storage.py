"""Pluggable controller-state storage (the GCS StoreClient seam).

Reference: `src/ray/gcs/store_client/` — `StoreClient` with in-memory,
file-system, and Redis backends behind one interface
(`store_client.h`, `redis_store_client.h:106`), which is what makes
GCS fault tolerance a deployment choice rather than a code path.

Here the durable unit is the controller SNAPSHOT (kv + jobs +
placement groups): backends implement atomic save/load of one snapshot
dict

    {"kv": {str: bytes}, "jobs": {str: dict}, "pgs": {str: dict},
     "ts": float}

- ``FileStoreClient``: json + base64, atomic rename (the default —
  survives head-process restart on one machine),
- ``SqliteStoreClient``: a real database file (WAL-free single-row
  blob), the durable tier playing the reference's Redis role for
  shared/network volumes,
- ``MemoryStoreClient``: an in-process snapshot holder for TESTING
  the seam (the reference's in-memory default).

`store_client_for(url)` picks by scheme: bare paths and ``file://``
map to file, ``sqlite://`` to sqlite; ``memory://`` resolves to None —
"no durability" means the controller skips the persist loop entirely
rather than serializing snapshots nobody can ever load.  Custom
backends register via `register_store_scheme`.
"""

from __future__ import annotations

import base64
import contextlib
import json
import logging
import os
import pickle
import sqlite3
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.core import serialization

logger = logging.getLogger(__name__)

Snapshot = Dict[str, Any]


class StoreClient:
    def load(self) -> Optional[Snapshot]:
        """Latest snapshot, or None when nothing was stored."""
        raise NotImplementedError

    def save(self, snapshot: Snapshot) -> None:
        """Durably replace the stored snapshot; raise on failure."""
        raise NotImplementedError


class MemoryStoreClient(StoreClient):
    def __init__(self):
        self._snap: Optional[Snapshot] = None

    def load(self) -> Optional[Snapshot]:
        return self._snap

    def save(self, snapshot: Snapshot) -> None:
        self._snap = dict(snapshot)


class FileStoreClient(StoreClient):
    """json+base64 with atomic rename (the original controller
    persistence format — existing snapshot files keep loading).

    I/O rides the `core/diskio.py` chokepoint, so DiskChaos covers
    controller persistence too, and each save embeds a checksum over
    the encoded body (`core/integrity.py`).  A snapshot that fails
    verification on load is treated as ABSENT — the controller boots
    fresh rather than adopting silently corrupted cluster state —
    and the event is counted (`rt_object_integrity_errors_total`,
    path="snapshot").  Pre-checksum snapshot files carry no "crc"
    field and load unverified (back-compat)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[Snapshot]:
        from ray_tpu.core import diskio as _diskio
        from ray_tpu.core import integrity as _integrity

        if not os.path.exists(self.path):
            return None
        raw = json.loads(_diskio.read_file(self.path).decode())
        crc = raw.pop("crc", None)
        algo = raw.pop("crc_algo", None)
        if crc is not None:
            body = json.dumps(raw, default=str, sort_keys=True).encode()
            if not _integrity.verify(body, crc, algo):
                try:
                    from ray_tpu.metrics import metric_defs as _md

                    _md.metric("rt_object_integrity_errors_total").inc(
                        tags={"path": "snapshot"}
                    )
                except Exception as e:
                    logger.debug("snapshot metric failed: %s", e)
                logger.error(
                    "controller snapshot %s failed checksum "
                    "verification; ignoring it (boot fresh)", self.path,
                )
                return None
        return {
            "kv": {
                k: base64.b64decode(v)
                for k, v in raw.get("kv", {}).items()
            },
            "jobs": raw.get("jobs", {}),
            "pgs": raw.get("pgs", {}),
            "ts": raw.get("ts", 0.0),
        }

    def save(self, snapshot: Snapshot) -> None:
        from ray_tpu.core import diskio as _diskio
        from ray_tpu.core import integrity as _integrity

        enc = {
            "kv": {
                k: base64.b64encode(bytes(v)).decode()
                for k, v in snapshot.get("kv", {}).items()
            },
            "jobs": snapshot.get("jobs", {}),
            "pgs": snapshot.get("pgs", {}),
            "ts": snapshot.get("ts", time.time()),
        }
        body = json.dumps(enc, default=str, sort_keys=True).encode()
        enc["crc"] = _integrity.checksum(body)
        enc["crc_algo"] = _integrity.ALGO
        _diskio.write_file(
            self.path, json.dumps(enc, default=str).encode()
        )


class SqliteStoreClient(StoreClient):
    """Single-row pickled snapshot in a sqlite file: transactional
    durability from the database, concurrent-reader safe.  A fresh
    connection per op keeps it thread-agnostic (saves come from the
    flush tick AND the shutdown path)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._tx() as c:
            c.execute(
                "CREATE TABLE IF NOT EXISTS snapshot ("
                "id INTEGER PRIMARY KEY CHECK (id = 1), data BLOB)"
            )

    @contextlib.contextmanager
    def _tx(self):
        # one fresh connection per op (thread-agnostic); 'with conn'
        # only wraps the transaction — the handle must be closed
        # explicitly or every op leaks a file descriptor
        with contextlib.closing(
            sqlite3.connect(self.path, timeout=10)
        ) as conn, conn:
            yield conn

    def load(self) -> Optional[Snapshot]:
        with self._tx() as c:
            row = c.execute(
                "SELECT data FROM snapshot WHERE id = 1"
            ).fetchone()
        # local trusted file, but unpickling still routes through the
        # audited chokepoint (core/serialization.loads)
        return serialization.loads(row[0]) if row else None

    def save(self, snapshot: Snapshot) -> None:
        blob = pickle.dumps(snapshot, protocol=pickle.HIGHEST_PROTOCOL)
        with self._tx() as c:
            c.execute(
                "INSERT INTO snapshot (id, data) VALUES (1, ?) "
                "ON CONFLICT (id) DO UPDATE SET data = excluded.data",
                (blob,),
            )


_SCHEMES: Dict[str, Callable[[str], Optional[StoreClient]]] = {
    "file": FileStoreClient,
    "sqlite": SqliteStoreClient,
    "memory": lambda _path: None,  # explicit no-durability choice
}


def register_store_scheme(scheme: str,
                          factory: Callable[[str], StoreClient]) -> None:
    _SCHEMES[scheme] = factory


def store_client_for(url: Optional[str]) -> Optional[StoreClient]:
    """None/empty -> no persistence; bare path -> file; else by
    scheme ('sqlite:///var/rt/state.db', 'memory://', ...)."""
    if not url:
        return None
    if "://" not in url:
        return FileStoreClient(url)
    scheme, _, rest = url.partition("://")
    factory = _SCHEMES.get(scheme)
    if factory is None:
        raise ValueError(
            f"unknown controller store scheme {scheme!r}; "
            f"registered: {sorted(_SCHEMES)}"
        )
    return factory(rest)
