"""Mixtral-style sparse-MoE transformer, TPU-first.

The expert-parallel model family (SURVEY §2.5: EP/MoE is absent from
the reference and first-class here).  Architecture = the Llama lineage
(RMSNorm, RoPE, GQA attention — reused from `models/llama.py`) with the
dense SwiGLU MLP replaced by a top-k routed mixture of experts
(`parallel/moe.py`: capacity-slot dispatch, Switch-style load-balance
aux loss, `lax.all_to_all` over the `ep` mesh axis under shard_map).

Same design stance as gpt2/llama: explicit param pytrees + pure
functions, blocks stacked under `lax.scan` (one compiled block body),
logical-axis tree so TP/FSDP/EP are rule-table swaps, bf16 compute.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import _apply, _rms_norm, _rope
from ray_tpu.parallel.moe import MoEConfig, init_moe, moe_forward
from ray_tpu.parallel.ring_attention import select_attention


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    intermediate: int = 14336  # per-expert hidden
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    router_aux_coef: float = 0.02  # load-balance loss weight
    dtype: Any = jnp.bfloat16
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def moe(self) -> MoEConfig:
        return MoEConfig(
            dim=self.dim, hidden=self.intermediate,
            num_experts=self.num_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor, dtype=self.dtype,
        )

    @staticmethod
    def mixtral_8x7b() -> "MixtralConfig":
        return MixtralConfig()

    @staticmethod
    def tiny(vocab_size: int = 256) -> "MixtralConfig":
        return MixtralConfig(
            vocab_size=vocab_size, max_seq_len=128, dim=64, n_layers=2,
            n_heads=4, n_kv_heads=2, intermediate=96, num_experts=4,
            top_k=2,
        )


# ----------------------------------------------------------------------
# init
# ----------------------------------------------------------------------
def init_params(cfg: MixtralConfig, key: jax.Array) -> Dict:
    ka = jax.random.split(key, 6)
    L, E = cfg.n_layers, cfg.dim
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    std = 0.02
    proj_std = std / math.sqrt(2 * L)

    def n(k, shape, s=std):
        return jax.random.normal(k, shape, dtype=jnp.float32) * s

    moe_keys = jax.random.split(ka[5], L)
    moe_layers = [init_moe(cfg.moe, mk) for mk in moe_keys]
    moe_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *moe_layers)

    return {
        "tok_emb": n(ka[0], (cfg.vocab_size, E)),
        "blocks": {
            "attn_norm": jnp.ones((L, E)),
            "wq": n(ka[1], (L, E, H * hd)),
            "wk": n(ka[2], (L, E, KV * hd)),
            "wv": n(ka[3], (L, E, KV * hd)),
            "wo": n(ka[4], (L, H * hd, E), proj_std),
            "moe_norm": jnp.ones((L, E)),
            **{f"moe_{k}": v for k, v in moe_stacked.items()},
        },
        "final_norm": jnp.ones((E,)),
        "lm_head": n(jax.random.fold_in(ka[0], 1), (E, cfg.vocab_size)),
    }


def logical_axes(cfg: MixtralConfig) -> Dict:
    return {
        "tok_emb": ("vocab", "embed"),
        "blocks": {
            "attn_norm": (None, "embed"),
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "heads"),
            "wv": (None, "embed", "heads"),
            "wo": (None, "heads", "embed"),
            "moe_norm": (None, "embed"),
            # expert axis shards over `ep` (rule table maps it)
            "moe_router": (None, "embed", None),
            "moe_w_in": (None, "expert", "embed", "mlp"),
            "moe_w_out": (None, "expert", "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def forward(cfg: MixtralConfig, params: Dict, tokens: jax.Array,
            mesh=None) -> Tuple[jax.Array, Dict]:
    """tokens [B, T] int32 -> (logits [B, T, vocab] f32,
    aux {load_balance_loss} averaged over layers)."""
    B, T = tokens.shape
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    group = H // KV
    moe_cfg = cfg.moe

    def body(x, layer):
        moe_params = {
            "router": layer["moe_router"],
            "w_in": layer["moe_w_in"],
            "w_out": layer["moe_w_out"],
        }

        def one(xin):
            h = _rms_norm(xin, layer["attn_norm"].astype(cfg.dtype),
                          cfg.norm_eps)
            q = _apply(h, layer["wq"], cfg.dtype)
            k = _apply(h, layer["wk"], cfg.dtype)
            v = _apply(h, layer["wv"], cfg.dtype)
            q = _rope(q.reshape(B, T, H, hd), cfg.rope_theta)
            k = _rope(k.reshape(B, T, KV, hd), cfg.rope_theta)
            v = v.reshape(B, T, KV, hd)
            if group > 1:
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            o = select_attention(cfg.attention, q, k, v, mesh, causal=True)
            o = o.reshape(B, T, H * hd)
            x1 = xin + _apply(o, layer["wo"], cfg.dtype)

            h2 = _rms_norm(x1, layer["moe_norm"].astype(cfg.dtype),
                           cfg.norm_eps)
            moe_out, aux = moe_forward(moe_cfg, moe_params, h2, mesh)
            return x1 + moe_out, aux["load_balance_loss"]

        fn = jax.checkpoint(one) if cfg.remat else one
        out, aux_loss = fn(x)
        return out, aux_loss

    x = x.astype(cfg.dtype)
    x, aux_losses = lax.scan(body, x, dict(params["blocks"]))
    x = _rms_norm(x, params["final_norm"].astype(cfg.dtype), cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"load_balance_loss": jnp.mean(aux_losses)}


def loss_fn(cfg: MixtralConfig, params: Dict, tokens: jax.Array,
            mesh=None) -> Tuple[jax.Array, Dict]:
    """Next-token CE + router load-balance aux (reference to the MoE
    literature: Switch/Mixtral train with an aux coefficient)."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward(cfg, params, inputs, mesh)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = jnp.mean(lse - tgt)
    total = ce + cfg.router_aux_coef * aux["load_balance_loss"]
    return total, {"ce_loss": ce, **aux}


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


def active_params_per_token(cfg: MixtralConfig, params) -> int:
    """Parameters touched per token (the MoE efficiency headline): all
    non-expert weights + top_k experts' FFNs."""
    total = num_params(params)
    expert_ffn = (
        cfg.n_layers * cfg.num_experts * 2 * cfg.dim * cfg.intermediate
    )
    active_ffn = (
        cfg.n_layers * cfg.top_k * 2 * cfg.dim * cfg.intermediate
    )
    return total - expert_ffn + active_ffn


# ----------------------------------------------------------------------
# train step
# ----------------------------------------------------------------------
def make_train_step(cfg: MixtralConfig, optimizer, mesh=None):
    def step(params, opt_state, tokens):
        (loss, metrics), grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, mesh), has_aux=True
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, **metrics}

    return step
