"""Llama family, TPU-first.

The fine-tune/serving flagship (BASELINE configs #4/#5: Llama-2 7B LoRA
fine-tune via XLA SPMD; Llama-3-style serving replicas).  Same design
stance as gpt2.py: explicit param pytrees + pure functions, stacked
blocks under `lax.scan` (one compiled block body), logical-axis tree so
TP/FSDP/SP are rule-table swaps, bf16 compute against f32 masters.

Architecture (Llama-2/3 lineage): RMSNorm, rotary position embeddings,
grouped-query attention, SwiGLU MLP, untied LM head.

LoRA is first-class: a separate low-rank adapter pytree; the forward
computes `x@W + (x@A)@B * scale` without materializing merged weights,
and the LoRA train step differentiates the adapter tree only — the
XLA-SPMD equivalent of the reference's torch/peft integration path
(`train/examples/deepspeed/`, `train/lightning/_lightning_utils.py`).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ring_attention import plain_attention, select_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32  # < n_heads => grouped-query attention
    intermediate: int = 11008
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama2_7b() -> "LlamaConfig":
        return LlamaConfig()

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, max_seq_len=8192, dim=4096, n_layers=32,
            n_heads=32, n_kv_heads=8, intermediate=14336, rope_theta=500000.0,
        )

    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=vocab_size, max_seq_len=128, dim=64, n_layers=2,
            n_heads=4, n_kv_heads=2, intermediate=128,
        )


# ----------------------------------------------------------------------
# init
# ----------------------------------------------------------------------
def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict:
    k = jax.random.split(key, 9)
    L, E = cfg.n_layers, cfg.dim
    hd, H, KV, I = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.intermediate
    std = 0.02
    proj_std = std / math.sqrt(2 * L)

    def n(key, shape, s=std):
        return jax.random.normal(key, shape, dtype=jnp.float32) * s

    return {
        "tok_emb": n(k[0], (cfg.vocab_size, E)),  # head uses its own key
        "blocks": {
            "attn_norm": jnp.ones((L, E)),
            "wq": n(k[1], (L, E, H * hd)),
            "wk": n(k[2], (L, E, KV * hd)),
            "wv": n(k[3], (L, E, KV * hd)),
            "wo": n(k[4], (L, H * hd, E), proj_std),
            "mlp_norm": jnp.ones((L, E)),
            "w_gate": n(k[5], (L, E, I)),
            "w_up": n(k[6], (L, E, I)),
            "w_down": n(k[7], (L, I, E), proj_std),
        },
        "final_norm": jnp.ones((E,)),
        "lm_head": n(k[8], (E, cfg.vocab_size)),
    }


def logical_axes(cfg: LlamaConfig) -> Dict:
    return {
        "tok_emb": ("vocab", "embed"),
        "blocks": {
            "attn_norm": (None, "embed"),
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "heads"),
            "wv": (None, "embed", "heads"),
            "wo": (None, "heads", "embed"),
            "mlp_norm": (None, "embed"),
            "w_gate": (None, "embed", "mlp"),
            "w_up": (None, "embed", "mlp"),
            "w_down": (None, "mlp", "embed"),
        },
        "final_norm": ("embed",),
        "lm_head": ("embed", "vocab"),
    }


# ----------------------------------------------------------------------
# LoRA adapters
# ----------------------------------------------------------------------
LORA_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora(cfg: LlamaConfig, key: jax.Array, rank: int = 8,
              alpha: float = 16.0,
              targets: Tuple[str, ...] = LORA_TARGETS) -> Dict:
    """Adapter pytree: per target, A [L, in, r] (gaussian) and
    B [L, r, out] (zeros — adapters start as identity)."""
    L = cfg.n_layers
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    dims = {
        "wq": (cfg.dim, H * hd),
        "wk": (cfg.dim, KV * hd),
        "wv": (cfg.dim, KV * hd),
        "wo": (H * hd, cfg.dim),
        "w_gate": (cfg.dim, cfg.intermediate),
        "w_up": (cfg.dim, cfg.intermediate),
        "w_down": (cfg.intermediate, cfg.dim),
    }
    ks = jax.random.split(key, len(targets))
    blocks = {}
    for t, kk in zip(targets, ks):
        din, dout = dims[t]
        blocks[f"{t}_a"] = (
            jax.random.normal(kk, (L, din, rank), jnp.float32) / math.sqrt(din)
        )
        blocks[f"{t}_b"] = jnp.zeros((L, rank, dout), jnp.float32)
    return {"blocks": blocks, "scale": jnp.asarray(alpha / rank, jnp.float32)}


def lora_logical_axes(cfg: LlamaConfig, lora: Dict) -> Dict:
    """A: input dim sharded like the base input ('embed'/'heads'/'mlp');
    r replicated.  B: r replicated; output like the base output."""
    in_ax = {"wq": "embed", "wk": "embed", "wv": "embed", "wo": "heads",
             "w_gate": "embed", "w_up": "embed", "w_down": "mlp"}
    out_ax = {"wq": "heads", "wk": "heads", "wv": "heads", "wo": "embed",
              "w_gate": "mlp", "w_up": "mlp", "w_down": "embed"}
    blocks = {}
    for name in lora["blocks"]:
        t, kind = name.rsplit("_", 1)
        if kind == "a":
            blocks[name] = (None, in_ax[t], None)
        else:
            blocks[name] = (None, None, out_ax[t])
    return {"blocks": blocks, "scale": ()}


def _apply(x, w, dtype, lora_layer=None, name: str = "", scale=None):
    """x @ w with an optional low-rank delta.  `scale` (per-OUTPUT-
    channel, from `quantize_weights_int8`) dequantizes int8 weights on
    the fly: (x @ q) * scale == x @ (q * scale) exactly, because the
    scale is constant along the contraction axis — the matmul runs on
    the int8 payload (upcast to the compute dtype) and HBM only ever
    streams 1 byte/weight."""
    out = x @ w.astype(dtype)
    if scale is not None:
        out = out * scale.astype(dtype)
    if lora_layer is not None and f"{name}_a" in lora_layer:
        a = lora_layer[f"{name}_a"].astype(dtype)
        b = lora_layer[f"{name}_b"].astype(dtype)
        out = out + ((x @ a) @ b) * lora_layer["__scale__"].astype(dtype)
    return out


def _lm_head(x, params, dtype):
    """Final projection to vocab logits in f32, int8-aware (sibling
    `lm_head_scale` leaf => per-vocab-column dequant after the matmul)."""
    logits = x @ params["lm_head"].astype(dtype)
    scale = params.get("lm_head_scale")
    if scale is not None:
        logits = logits * scale.astype(dtype)
    return logits.astype(jnp.float32)


# weights the serve path quantizes; norms and the embedding lookup stay
# in their original dtype (tiny, and tok_emb is a gather, not a matmul)
QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weights_int8(params: Dict) -> Dict:
    """Symmetric per-output-channel int8 weights for serving.

    Every matmul weight (block projections + lm_head) becomes an int8
    payload with a sibling `<name>_scale` f32 leaf holding one scale
    per output channel (`[L, out]` for blocks, `[vocab]` for the
    head).  The scale axis rides the blocks' layer-scan like any other
    leaf, so `forward` / `decode_step*` pick it up via
    `layer.get("<name>_scale")` with zero structural change; `_apply`
    multiplies it back in after the matmul, which is exact w.r.t.
    scaling because the scale is constant along the contraction.
    Quantization error is the int8 rounding of each weight (<= scale/2
    per element); `tests/test_paged_attention.py` gates greedy argmax
    agreement + bounded logit error on the tiny model."""
    from ray_tpu.ops.paged_attention import quantize_int8

    out = {k: v for k, v in params.items()}
    blocks = dict(out["blocks"])
    for name in QUANT_TARGETS:
        q, s = quantize_int8(blocks[name], axis=1)  # [L,in,out] -> [L,out]
        blocks[name] = q
        blocks[name + "_scale"] = s
    out["blocks"] = blocks
    q, s = quantize_int8(out["lm_head"], axis=0)  # [E,vocab] -> [vocab]
    out["lm_head"] = q
    out["lm_head_scale"] = s
    return out


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
def _rms_norm(x, g, eps):
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(ms + eps).astype(x.dtype)) * g


def _rope(x, theta: float, t0=0):
    """Rotary embedding over the last dim; x [B, T, H, hd].  t0 may be
    a traced offset (KV-cached decode positions)."""
    B, T, H, hd = x.shape
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = jnp.asarray(t0, jnp.float32) + jnp.arange(T, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]  # [T, half]
    cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def forward(cfg: LlamaConfig, params: Dict, tokens: jax.Array,
            mesh=None, lora: Optional[Dict] = None,
            return_kv: bool = False):
    """tokens [B, T] int32 -> logits [B, T, vocab] (f32).

    With return_kv=True also returns the per-layer post-RoPE K/V
    ([L, B, T, KV, hd] each) — the prefill path of KV-cached decoding
    (reference capability: vLLM-style serving on Ray; here the native
    inference path for serve replicas).
    """
    B, T = tokens.shape
    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    group = H // KV

    blocks = params["blocks"]
    lora_blocks = None
    if lora is not None:
        lora_blocks = dict(lora["blocks"])

    def body(x, layer):
        if lora is not None:
            layer_lora = {k: v for k, v in layer.items() if k.endswith(("_a", "_b"))}
            layer_lora["__scale__"] = lora["scale"]
            layer = {k: v for k, v in layer.items() if not k.endswith(("_a", "_b"))}
        else:
            layer_lora = None

        def one(xin):
            h = _rms_norm(xin, layer["attn_norm"].astype(cfg.dtype), cfg.norm_eps)
            q = _apply(h, layer["wq"], cfg.dtype, layer_lora, "wq",
                       layer.get("wq_scale"))
            k = _apply(h, layer["wk"], cfg.dtype, layer_lora, "wk",
                       layer.get("wk_scale"))
            v = _apply(h, layer["wv"], cfg.dtype, layer_lora, "wv",
                       layer.get("wv_scale"))
            q = _rope(q.reshape(B, T, H, hd), cfg.rope_theta)
            k_kv = _rope(k.reshape(B, T, KV, hd), cfg.rope_theta)
            v_kv = v.reshape(B, T, KV, hd)
            k, v = k_kv, v_kv
            if group > 1:  # GQA: each kv head serves `group` query heads
                k = jnp.repeat(k, group, axis=2)
                v = jnp.repeat(v, group, axis=2)
            o = select_attention(cfg.attention, q, k, v, mesh, causal=True)
            o = o.reshape(B, T, H * hd)
            x1 = xin + _apply(o, layer["wo"], cfg.dtype, layer_lora, "wo",
                              layer.get("wo_scale"))

            h2 = _rms_norm(x1, layer["mlp_norm"].astype(cfg.dtype), cfg.norm_eps)
            gate = _apply(h2, layer["w_gate"], cfg.dtype, layer_lora,
                          "w_gate", layer.get("w_gate_scale"))
            up = _apply(h2, layer["w_up"], cfg.dtype, layer_lora, "w_up",
                        layer.get("w_up_scale"))
            down = _apply(
                jax.nn.silu(gate) * up, layer["w_down"], cfg.dtype,
                layer_lora, "w_down", layer.get("w_down_scale"),
            )
            return x1 + down, k_kv, v_kv

        fn = jax.checkpoint(one) if cfg.remat else one
        out, k_kv, v_kv = fn(x)
        return out, ((k_kv, v_kv) if return_kv else None)

    scan_tree = dict(blocks)
    if lora_blocks is not None:
        scan_tree.update(lora_blocks)
    x = x.astype(cfg.dtype)
    x, kv = lax.scan(body, x, scan_tree)
    x = _rms_norm(x, params["final_norm"].astype(cfg.dtype), cfg.norm_eps)
    logits = _lm_head(x, params, cfg.dtype)
    if return_kv:
        return logits, kv
    return logits


def loss_fn(cfg: LlamaConfig, params: Dict, tokens: jax.Array,
            mesh=None, lora: Optional[Dict] = None) -> jax.Array:
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(cfg, params, inputs, mesh, lora)
    # lse - target_logit == -log_softmax[target] without materializing
    # the full [B, T, vocab] log-prob tensor (see gpt2.loss_fn)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt)


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# ----------------------------------------------------------------------
# train steps
# ----------------------------------------------------------------------
def make_train_step(cfg: LlamaConfig, optimizer, mesh=None):
    """Full fine-tune/pretrain step."""

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, mesh)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    return step


def make_lora_train_step(cfg: LlamaConfig, optimizer, mesh=None):
    """LoRA step: base params frozen, gradients flow only through the
    adapter pytree (the memory/steps win that makes 7B tuning fit)."""

    def step(base_params, lora_params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda lp: loss_fn(cfg, base_params, tokens, mesh, lora=lp)
        )(lora_params)
        updates, opt_state = optimizer.update(grads, opt_state, lora_params)
        import optax

        lora_params = optax.apply_updates(lora_params, updates)
        return lora_params, opt_state, {"loss": loss}

    return step


def merge_lora(cfg: LlamaConfig, params: Dict, lora: Dict) -> Dict:
    """Bake adapters into the base weights (for serving without the
    adapter matmuls)."""
    out = jax.tree.map(lambda x: x, params)  # shallow-ish copy
    blocks = dict(out["blocks"])
    scale = lora["scale"]
    for name, a in lora["blocks"].items():
        t, kind = name.rsplit("_", 1)
        if kind != "a":
            continue
        b = lora["blocks"][f"{t}_b"]
        blocks[t] = blocks[t] + jnp.einsum("lir,lro->lio", a, b) * scale
    out["blocks"] = blocks
    return out


# ----------------------------------------------------------------------
# KV-cached decoding (the serving inference path)
# ----------------------------------------------------------------------
def forward_with_prefix(cfg: LlamaConfig, params: Dict, tokens: jax.Array,
                        prefix_kv, prefix_len):
    """Suffix forward over an existing prefix KV cache (radix prefix
    reuse: the paged engine's cache-hit prefill path).

    `tokens` [B, S] is the prompt SUFFIX, living at absolute positions
    `prefix_len`..`prefix_len + S - 1`; `prefix_kv` = (k, v), each
    [L, B, Pmax, KV, hd], the gathered (possibly padded) KV of the
    shared prefix — columns at or beyond `prefix_len` are masked out,
    so block-table padding rows cost nothing but FLOPs.  Returns
    (full-suffix logits [B, S, vocab] f32, (k_suf, v_suf) each
    [L, B, S, KV, hd]) — the suffix KV the caller writes into its own
    cache blocks.

    Numerics deliberately mirror `forward`'s dense path
    (`plain_attention`: same einsum forms, same -1e30 mask, softmax in
    the compute dtype) so a prefix-cached prefill produces the same
    greedy tokens as the full-prompt prefill it replaces;
    `tests/test_llm_engine.py` pins the equivalence.
    """
    pk, pv = prefix_kv  # [L, B, Pmax, KV, hd]
    B, S = tokens.shape
    Pmax = pk.shape[2]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    group = H // KV
    scale = hd ** -0.5

    x = params["tok_emb"].astype(cfg.dtype)[tokens]
    # column validity over the concatenated [Pmax + S] axis: live
    # prefix columns, then causal self-attention within the suffix
    cols = jnp.arange(Pmax + S)
    prefix_ok = (cols < prefix_len) & (cols < Pmax)
    suffix_causal = (
        (cols[None, :] >= Pmax)
        & ((cols[None, :] - Pmax) <= jnp.arange(S)[:, None])
    )
    mask = (prefix_ok[None, :] | suffix_causal)[None, None]  # [1,1,S,P+S]

    def body(x, inputs):
        layer, pk_l, pv_l = inputs  # pk_l/pv_l [B, Pmax, KV, hd]
        h = _rms_norm(x, layer["attn_norm"].astype(cfg.dtype), cfg.norm_eps)
        q = _apply(h, layer["wq"], cfg.dtype, scale=layer.get("wq_scale"))
        k = _apply(h, layer["wk"], cfg.dtype, scale=layer.get("wk_scale"))
        v = _apply(h, layer["wv"], cfg.dtype, scale=layer.get("wv_scale"))
        q = _rope(q.reshape(B, S, H, hd), cfg.rope_theta, t0=prefix_len)
        k_suf = _rope(k.reshape(B, S, KV, hd), cfg.rope_theta, t0=prefix_len)
        v_suf = v.reshape(B, S, KV, hd)
        kk = jnp.concatenate([pk_l.astype(cfg.dtype), k_suf], axis=1)
        vv = jnp.concatenate([pv_l.astype(cfg.dtype), v_suf], axis=1)
        if group > 1:  # GQA: each kv head serves `group` query heads
            kk = jnp.repeat(kk, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kk) * scale
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, vv)
        o = o.reshape(B, S, H * hd)
        x1 = x + _apply(o, layer["wo"], cfg.dtype,
                        scale=layer.get("wo_scale"))

        h2 = _rms_norm(x1, layer["mlp_norm"].astype(cfg.dtype), cfg.norm_eps)
        gate = _apply(h2, layer["w_gate"], cfg.dtype,
                      scale=layer.get("w_gate_scale"))
        up = _apply(h2, layer["w_up"], cfg.dtype,
                    scale=layer.get("w_up_scale"))
        down = _apply(jax.nn.silu(gate) * up, layer["w_down"], cfg.dtype,
                      scale=layer.get("w_down_scale"))
        return x1 + down, (k_suf, v_suf)

    x = x.astype(cfg.dtype)
    x, kv = lax.scan(body, x, (dict(params["blocks"]), pk, pv))
    x = _rms_norm(x, params["final_norm"].astype(cfg.dtype), cfg.norm_eps)
    logits = _lm_head(x, params, cfg.dtype)
    return logits, kv


def prefill(cfg: LlamaConfig, params: Dict, tokens: jax.Array,
            max_len: int, mesh=None):
    """Process the prompt in one pass and build the KV cache.

    tokens [B, T] -> (last-position logits [B, vocab],
    cache = (k [L, B, max_len, KV, hd], v [...]), length T).
    Reference capability: the prefill phase of LLM serving (the
    vLLM-on-Ray pattern); here a native jittable function.
    """
    B, T = tokens.shape
    logits, (ks, vs) = forward(cfg, params, tokens, mesh, return_kv=True)
    pad = [(0, 0), (0, 0), (0, max_len - T), (0, 0), (0, 0)]
    k_cache = jnp.pad(ks, pad)
    v_cache = jnp.pad(vs, pad)
    return logits[:, -1, :], (k_cache, v_cache)


def decode_step(cfg: LlamaConfig, params: Dict, token: jax.Array,
                cache, pos):
    """One token of autoregressive decoding against the KV cache.

    SYNC CONTRACT with `decode_step_vec`: the vector-position variant
    duplicates this body on purpose — delegating would put its
    masked-select cache write (a full cache read+write per step) on
    this scalar hot path, which `generate`'s fused scan rides.  Any
    numerics change here must land in both;
    `tests/test_llm_engine.py::test_decode_step_vec_matches_scalar_pos`
    fails on divergence.

    token [B] int32, pos scalar (current sequence length) ->
    (logits [B, vocab], updated cache).  Static shapes throughout (the
    cache is max_len-sized and masked by position), so the step compiles
    once and every subsequent token reuses it.
    """
    k_cache, v_cache = cache  # [L, B, M, KV, hd]
    B = token.shape[0]
    M = k_cache.shape[2]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    group = H // KV

    x = params["tok_emb"].astype(cfg.dtype)[token][:, None, :]  # [B,1,d]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    # causal-by-position mask over the cache slots
    valid = (jnp.arange(M) <= pos)[None, None, :, None]  # [1,1,M,1]

    def body(x, inputs):
        layer, kc, vc = inputs  # kc/vc [B, M, KV, hd]
        h = _rms_norm(x, layer["attn_norm"].astype(cfg.dtype), cfg.norm_eps)
        q = _apply(h, layer["wq"], cfg.dtype, scale=layer.get("wq_scale"))
        k = _apply(h, layer["wk"], cfg.dtype, scale=layer.get("wk_scale"))
        v = _apply(h, layer["wv"], cfg.dtype, scale=layer.get("wv_scale"))
        q = _rope(q.reshape(B, 1, H, hd), cfg.rope_theta, t0=pos)
        k_new = _rope(k.reshape(B, 1, KV, hd), cfg.rope_theta, t0=pos)
        v_new = v.reshape(B, 1, KV, hd)
        kc = lax.dynamic_update_slice(kc, k_new.astype(kc.dtype),
                                      (0, pos, 0, 0))
        vc = lax.dynamic_update_slice(vc, v_new.astype(vc.dtype),
                                      (0, pos, 0, 0))
        kk, vv = kc, vc
        if group > 1:
            kk = jnp.repeat(kk, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
        # scores over all cache slots, masked beyond pos.  bf16
        # operands with f32 ACCUMULATION (flash-style numerics, the
        # standard decode form; measured equal to explicit .astype(f32)
        # operands on v5e — XLA fuses those casts — but this shape
        # guarantees no cache-sized f32 copy on any backend)
        s = jnp.einsum(
            "bohd,bmhd->bhom", q, kk,
            preferred_element_type=jnp.float32,
        ) * scale  # [B,H,1,M] f32
        s = jnp.where(valid.transpose(0, 3, 1, 2), s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum(
            "bhom,bmhd->bohd", w.astype(cfg.dtype), vv,
            preferred_element_type=jnp.float32,
        )
        o = o.astype(cfg.dtype).reshape(B, 1, H * hd)
        x1 = x + _apply(o, layer["wo"], cfg.dtype,
                        scale=layer.get("wo_scale"))

        h2 = _rms_norm(x1, layer["mlp_norm"].astype(cfg.dtype), cfg.norm_eps)
        gate = _apply(h2, layer["w_gate"], cfg.dtype,
                      scale=layer.get("w_gate_scale"))
        up = _apply(h2, layer["w_up"], cfg.dtype,
                    scale=layer.get("w_up_scale"))
        down = _apply(jax.nn.silu(gate) * up, layer["w_down"], cfg.dtype,
                      scale=layer.get("w_down_scale"))
        return x1 + down, (kc, vc)

    x = x.astype(cfg.dtype)
    x, (k_cache, v_cache) = lax.scan(
        body, x, (dict(params["blocks"]), k_cache, v_cache)
    )
    x = _rms_norm(x, params["final_norm"].astype(cfg.dtype), cfg.norm_eps)
    logits = _lm_head(x[:, 0, :], params, cfg.dtype)
    return logits, (k_cache, v_cache)


def _rope_at(x, theta: float, pos_b):
    """Rotary embedding for ONE decode step at PER-ROW positions:
    x [B, 1, H, hd], pos_b [B] int32 — the continuous-batching form,
    where every batch slot sits at its own sequence length."""
    B, T, H, hd = x.shape
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = pos_b.astype(jnp.float32)[:, None] * freqs[None, :]  # [B, half]
    cos = jnp.cos(ang)[:, None, None, :].astype(x.dtype)
    sin = jnp.sin(ang)[:, None, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                           axis=-1)


def decode_step_vec(cfg: LlamaConfig, params: Dict, token: jax.Array,
                    cache, pos):
    """One decode step with PER-ROW positions (continuous batching:
    every slot advances at its own length; reference capability: the
    vLLM-on-Ray serving pattern's step-level scheduling).

    token [B] int32, pos [B] int32 (current length per row) ->
    (logits [B, vocab] f32, updated cache).  Same math as
    `decode_step` restricted to equal positions; rows are independent,
    so a slot's tokens are identical to what a dedicated `generate`
    would produce.  Deliberately duplicates `decode_step`'s body (see
    its SYNC CONTRACT note): the masked-select write here must not tax
    the scalar path, and the parity test pins the two together."""
    k_cache, v_cache = cache  # [L, B, M, KV, hd]
    B = token.shape[0]
    M = k_cache.shape[2]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    group = H // KV

    x = params["tok_emb"].astype(cfg.dtype)[token][:, None, :]  # [B,1,d]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    # per-row causal mask over cache slots: [B, M]
    valid = jnp.arange(M)[None, :] <= pos[:, None]
    # per-row write mask for the cache update.  A masked SELECT, not a
    # batched scatter: `.at[arange(B), pos].set(...)` lowers to a
    # general scatter that TPU executes catastrophically slowly inside
    # the layer scan (measured ~30x the whole step's bandwidth cost);
    # the select is one dense read+write of the cache the step already
    # reads anyway.
    write = (jnp.arange(M)[None, :] == pos[:, None])[:, :, None, None]

    def body(x, inputs):
        layer, kc, vc = inputs  # kc/vc [B, M, KV, hd]
        h = _rms_norm(x, layer["attn_norm"].astype(cfg.dtype), cfg.norm_eps)
        q = _apply(h, layer["wq"], cfg.dtype, scale=layer.get("wq_scale"))
        k = _apply(h, layer["wk"], cfg.dtype, scale=layer.get("wk_scale"))
        v = _apply(h, layer["wv"], cfg.dtype, scale=layer.get("wv_scale"))
        q = _rope_at(q.reshape(B, 1, H, hd), cfg.rope_theta, pos)
        k_new = _rope_at(k.reshape(B, 1, KV, hd), cfg.rope_theta, pos)
        v_new = v.reshape(B, 1, KV, hd)
        kc = jnp.where(write, k_new.astype(kc.dtype), kc)
        vc = jnp.where(write, v_new.astype(vc.dtype), vc)
        kk, vv = kc, vc
        if group > 1:
            kk = jnp.repeat(kk, group, axis=2)
            vv = jnp.repeat(vv, group, axis=2)
        s = jnp.einsum(
            "bohd,bmhd->bhom", q, kk,
            preferred_element_type=jnp.float32,
        ) * scale  # [B,H,1,M] f32
        s = jnp.where(valid[:, None, None, :], s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum(
            "bhom,bmhd->bohd", w.astype(cfg.dtype), vv,
            preferred_element_type=jnp.float32,
        )
        o = o.astype(cfg.dtype).reshape(B, 1, H * hd)
        x1 = x + _apply(o, layer["wo"], cfg.dtype,
                        scale=layer.get("wo_scale"))

        h2 = _rms_norm(x1, layer["mlp_norm"].astype(cfg.dtype), cfg.norm_eps)
        gate = _apply(h2, layer["w_gate"], cfg.dtype,
                      scale=layer.get("w_gate_scale"))
        up = _apply(h2, layer["w_up"], cfg.dtype,
                    scale=layer.get("w_up_scale"))
        down = _apply(jax.nn.silu(gate) * up, layer["w_down"], cfg.dtype,
                      scale=layer.get("w_down_scale"))
        return x1 + down, (kc, vc)

    x = x.astype(cfg.dtype)
    x, (k_cache, v_cache) = lax.scan(
        body, x, (dict(params["blocks"]), k_cache, v_cache)
    )
    x = _rms_norm(x, params["final_norm"].astype(cfg.dtype), cfg.norm_eps)
    logits = _lm_head(x[:, 0, :], params, cfg.dtype)
    return logits, (k_cache, v_cache)


def decode_step_paged(cfg: LlamaConfig, params: Dict, token: jax.Array,
                      k_pool, v_pool, tables, pos, *, kv_scales=None,
                      interpret: Optional[bool] = None):
    """One decode step with PER-ROW positions straight off the paged
    KV pool — `decode_step_vec` with the dense gather/scatter replaced
    by the Pallas kernels in `ops/paged_attention.py`.

    token [B] int32; k_pool/v_pool [L, num_blocks, block_size, KV, hd]
    (the `BlockPool` tensors, passed WHOLE — the layer index rides the
    kernels as a scalar-prefetch arg, so the scan never slices the
    pool); tables [B, W] int32 block tables (scratch-block padded);
    pos [B] int32 per-row positions.  Per layer: `paged_kv_append`
    writes the new KV row in place, then `paged_decode_attention`
    walks each row's blocks with an online softmax.  Returns
    (logits [B, vocab] f32, k_pool, v_pool) — plus the updated
    (k_scale, v_scale) sidecar when `kv_scales` is given (int8 pools).

    Numerics mirror `decode_step_vec` (write-then-attend, f32 score
    accumulation, -1e30 mask, f32 softmax, weights cast to cfg.dtype
    for the value matmul); the reduction is blockwise-online, so
    logits agree to float rounding and greedy argmax is preserved
    (`tests/test_paged_attention.py` pins both).  Int8 weights ride
    the same `<name>_scale` leaves as the other decode paths."""
    from ray_tpu.ops import paged_attention as _pa

    B = token.shape[0]
    L = k_pool.shape[0]
    hd, H, KV = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    quant = kv_scales is not None

    x = params["tok_emb"].astype(cfg.dtype)[token][:, None, :]  # [B,1,d]

    def body(carry, inputs):
        if quant:
            x, kp, vp, ks, vs = carry
        else:
            x, kp, vp = carry
        li, layer = inputs
        h = _rms_norm(x, layer["attn_norm"].astype(cfg.dtype), cfg.norm_eps)
        q = _apply(h, layer["wq"], cfg.dtype, scale=layer.get("wq_scale"))
        k = _apply(h, layer["wk"], cfg.dtype, scale=layer.get("wk_scale"))
        v = _apply(h, layer["wv"], cfg.dtype, scale=layer.get("wv_scale"))
        q = _rope_at(q.reshape(B, 1, H, hd), cfg.rope_theta, pos)
        k_new = _rope_at(k.reshape(B, 1, KV, hd), cfg.rope_theta, pos)
        v_new = v.reshape(B, 1, KV, hd)
        if quant:
            kq, ks_new = _pa.quantize_int8(k_new[:, 0])
            vq, vs_new = _pa.quantize_int8(v_new[:, 0])
            kp, vp, ks, vs = _pa.paged_kv_append(
                kp, vp, kq, vq, tables, pos, li,
                k_scale=ks, v_scale=vs, k_new_scale=ks_new,
                v_new_scale=vs_new, interpret=interpret,
            )
            o = _pa.paged_decode_attention(
                q[:, 0], kp, vp, tables, pos, li,
                k_scale=ks, v_scale=vs, interpret=interpret,
            )
        else:
            kp, vp = _pa.paged_kv_append(
                kp, vp, k_new[:, 0].astype(kp.dtype),
                v_new[:, 0].astype(vp.dtype), tables, pos, li,
                interpret=interpret,
            )
            o = _pa.paged_decode_attention(
                q[:, 0], kp, vp, tables, pos, li, interpret=interpret,
            )
        o = o.astype(cfg.dtype).reshape(B, 1, H * hd)
        x1 = x + _apply(o, layer["wo"], cfg.dtype,
                        scale=layer.get("wo_scale"))

        h2 = _rms_norm(x1, layer["mlp_norm"].astype(cfg.dtype), cfg.norm_eps)
        gate = _apply(h2, layer["w_gate"], cfg.dtype,
                      scale=layer.get("w_gate_scale"))
        up = _apply(h2, layer["w_up"], cfg.dtype,
                    scale=layer.get("w_up_scale"))
        down = _apply(jax.nn.silu(gate) * up, layer["w_down"], cfg.dtype,
                      scale=layer.get("w_down_scale"))
        if quant:
            return (x1 + down, kp, vp, ks, vs), None
        return (x1 + down, kp, vp), None

    if quant:
        carry0 = (x.astype(cfg.dtype), k_pool, v_pool) + tuple(kv_scales)
    else:
        carry0 = (x.astype(cfg.dtype), k_pool, v_pool)
    xs = (jnp.arange(L, dtype=jnp.int32), dict(params["blocks"]))
    carry, _ = lax.scan(body, carry0, xs)
    x = _rms_norm(carry[0], params["final_norm"].astype(cfg.dtype),
                  cfg.norm_eps)
    logits = _lm_head(x[:, 0, :], params, cfg.dtype)
    return (logits,) + tuple(carry[1:])


_DECODE_JIT_CACHE: Dict = {}


def _jitted_generate_fn(cfg: LlamaConfig, max_new_tokens: int,
                        greedy: bool, mesh=None):
    """One fused prefill+decode program per (cfg, n_new, greedy): the
    WHOLE generation — prefill and a `lax.scan` over decode steps —
    compiles into a single XLA program, so a request costs ONE
    dispatch instead of `max_new_tokens` host round-trips.  On a real
    deployment the per-dispatch latency is what dominates small-batch
    decode (each python-loop step is a blocking device round-trip);
    scanning the loop on-device removes it entirely.  This is the
    compiler-friendly-control-flow rule applied to serving."""
    key_ = (cfg, max_new_tokens, greedy, id(mesh) if mesh else None)
    fn = _DECODE_JIT_CACHE.get(key_)
    if fn is not None:
        return fn

    def gen(params, prompt, temperature, rng):
        B, T = prompt.shape
        max_len = T + max_new_tokens

        def pick(logits, k):
            if greedy:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits / temperature, axis=-1
            ).astype(jnp.int32)

        keys = jax.random.split(rng, max_new_tokens)
        logits, cache = prefill(cfg, params, prompt, max_len, mesh)
        tok0 = pick(logits, keys[0])

        def body(carry, k_i):
            tok, cache, pos = carry
            logits, cache = decode_step(cfg, params, tok, cache, pos)
            nt = pick(logits, k_i)
            return (nt, cache, pos + 1), nt

        if max_new_tokens > 1:
            _, toks = lax.scan(
                body, (tok0, cache, jnp.asarray(T, jnp.int32)), keys[1:]
            )  # toks [n-1, B]
            return jnp.concatenate(
                [tok0[:, None], toks.transpose(1, 0)], axis=1
            )
        return tok0[:, None]

    fn = jax.jit(gen)
    # each entry retains compiled executables (host + device memory):
    # bound the cache so a long-lived server with badly-bucketed
    # callers degrades to recompiles, not to unbounded growth
    while len(_DECODE_JIT_CACHE) >= 32:
        _DECODE_JIT_CACHE.pop(next(iter(_DECODE_JIT_CACHE)))
    _DECODE_JIT_CACHE[key_] = fn
    return fn


def generate(cfg: LlamaConfig, params: Dict, prompt: jax.Array,
             max_new_tokens: int, temperature: float = 0.0,
             key: Optional[jax.Array] = None, mesh=None) -> jax.Array:
    """Autoregressive generation: fused prefill + KV-cached decode scan.

    prompt [B, T] int32 -> generated [B, max_new_tokens] int32.
    temperature 0 = greedy; otherwise softmax sampling with `key`.
    One compiled program per (B, T, max_new_tokens, greedy) shape — a
    whole generation is a single device dispatch (see
    `_jitted_generate_fn`); same-shape requests reuse the program.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    fn = _jitted_generate_fn(cfg, max_new_tokens, temperature <= 0.0, mesh)
    return fn(params, prompt,
              jnp.asarray(max(temperature, 1e-6), jnp.float32), key)
