"""Model families (the flagship workloads of the framework).

- gpt2: pretraining flagship (BASELINE #2; bench.py measures it)
- llama: fine-tune/serving flagship with first-class LoRA and
  KV-cached decoding (BASELINE #4/#5)
- mixtral: sparse-MoE family exercising expert parallelism over the
  `ep` mesh axis (SURVEY §2.5)
"""

from ray_tpu.models import gpt2, llama, mixtral

__all__ = ["gpt2", "llama", "mixtral"]
