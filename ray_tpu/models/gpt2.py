"""GPT-2 family, TPU-first.

The flagship model for the Train-equivalent (BASELINE config #2: GPT-2
124M pretraining).  Written as explicit param pytrees + pure functions
(idiomatic jax: transforms compose over it freely) with a parallel
*logical axis* tree so the same model runs under any mesh rule table —
DP, FSDP, TP, SP are sharding choices, not model edits (SURVEY §2.5).

TPU notes:
- matmuls run in bfloat16 against f32 master weights (MXU native);
- attention can be dense, ring (sequence-parallel over `sp`, long
  context), or Ulysses all-to-all — config flag, same weights;
- blocks are scanned (`lax.scan` over stacked layer params) so XLA
  compiles ONE block body regardless of depth — compile time stays flat
  and remat (`jax.checkpoint`) applies per-block.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.parallel.ring_attention import plain_attention, select_attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0  # pretraining default; applied only if >0
    dtype: Any = jnp.bfloat16  # compute dtype (params stay f32)
    attention: str = "dense"  # dense | flash | ring | ulysses
    remat: bool = True
    # lm-head logits dtype for the LOSS path: float32 (default) or
    # bfloat16 — bf16 halves the [B, T, vocab] HBM traffic (the
    # single largest tensor in the step) at ~1e-3 loss precision;
    # `forward()` always returns f32 logits for inference callers
    logits_dtype: Any = jnp.float32
    # layer-scan unroll factor: >1 lets XLA fuse/pipeline across block
    # boundaries at the cost of code size (any positive value; the scan
    # length is n_layer, or n_layer/2 under remat_policy="half")
    scan_unroll: int = 1
    # remat policy: "full" recomputes the whole block backward (min
    # memory); "dots" saves matmul outputs (checkpoint_policies
    # dots_with_no_batch_dims_saveable); "names" saves exactly the
    # tagged matmul inputs (see `_SAVED_NAMES`) so the backward
    # recomputes ONLY the attention score/prob internals — the
    # quadratic part — instead of the whole block (~15% of fwd FLOPs
    # recomputed vs 100% for "full", at ~750 MB/layer saved residuals
    # for the 124M bench shapes)
    remat_policy: str = "full"
    # layers exempted from remat (the LAST `remat_skip` of the stack
    # keep their activations resident and skip the backward's forward
    # replay).  Sized to HBM headroom: each exempt layer trades ~1.1 GB
    # of saved activations (124M bench shapes, batch 32) for 1/n_layer
    # of the remat recompute — the knob between "full" (min memory) and
    # remat off (min FLOPs)
    remat_skip: int = 0

    def __post_init__(self):
        if self.remat_policy not in ("full", "dots", "names", "half"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; "
                "expected 'full', 'dots', 'names', or 'half'"
            )
        if self.remat_policy == "half" and self.n_layer % 2:
            raise ValueError("remat_policy='half' needs an even n_layer")
        if self.scan_unroll < 1:
            raise ValueError("scan_unroll must be >= 1")
        if not 0 <= self.remat_skip <= self.n_layer:
            raise ValueError(
                f"remat_skip must be in [0, n_layer], got {self.remat_skip}"
            )
        if self.remat_skip and self.remat_policy != "full":
            raise ValueError(
                "remat_skip composes with remat_policy='full' only"
            )

    @property
    def head_dim(self) -> int:
        return self.n_embd // self.n_head

    @staticmethod
    def gpt2_124m() -> "GPT2Config":
        return GPT2Config()

    @staticmethod
    def tiny(vocab_size: int = 512) -> "GPT2Config":
        return GPT2Config(
            vocab_size=vocab_size, n_positions=128, n_embd=64, n_layer=2, n_head=4
        )


# ----------------------------------------------------------------------
# init
# ----------------------------------------------------------------------
def init_params(cfg: GPT2Config, key: jax.Array) -> Dict:
    """Stacked-block layout: block params have a leading n_layer dim so
    the forward pass scans over them."""
    k = jax.random.split(key, 8)
    std = 0.02
    L, E, H = cfg.n_layer, cfg.n_embd, 4 * cfg.n_embd
    proj_std = std / math.sqrt(2 * cfg.n_layer)

    def n(key, shape, s=std):
        return jax.random.normal(key, shape, dtype=jnp.float32) * s

    return {
        "wte": n(k[0], (cfg.vocab_size, E)),
        "wpe": n(k[1], (cfg.n_positions, E), 0.01),
        "blocks": {
            "ln1_g": jnp.ones((L, E)),
            "ln1_b": jnp.zeros((L, E)),
            "attn_qkv_w": n(k[2], (L, E, 3 * E)),
            "attn_qkv_b": jnp.zeros((L, 3 * E)),
            "attn_out_w": n(k[3], (L, E, E), proj_std),
            "attn_out_b": jnp.zeros((L, E)),
            "ln2_g": jnp.ones((L, E)),
            "ln2_b": jnp.zeros((L, E)),
            "mlp_fc_w": n(k[4], (L, E, H)),
            "mlp_fc_b": jnp.zeros((L, H)),
            "mlp_out_w": n(k[5], (L, H, E), proj_std),
            "mlp_out_b": jnp.zeros((L, E)),
        },
        "lnf_g": jnp.ones((E,)),
        "lnf_b": jnp.zeros((E,)),
    }


def logical_axes(cfg: GPT2Config) -> Dict:
    """Logical-axis tree matching init_params; mapped to mesh axes by
    `ray_tpu.parallel.sharding` rules (leading None = stacked layer dim)."""
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": {
            "ln1_g": (None, "embed"),
            "ln1_b": (None, "embed"),
            "attn_qkv_w": (None, "embed", "heads"),
            "attn_qkv_b": (None, "heads"),
            "attn_out_w": (None, "heads", "embed"),
            "attn_out_b": (None, "embed"),
            "ln2_g": (None, "embed"),
            "ln2_b": (None, "embed"),
            "mlp_fc_w": (None, "embed", "mlp"),
            "mlp_fc_b": (None, "mlp"),
            "mlp_out_w": (None, "mlp", "embed"),
            "mlp_out_b": (None, "embed"),
        },
        "lnf_g": ("embed",),
        "lnf_b": ("embed",),
    }


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------
# Activations saved (not recomputed) under remat_policy="names": every
# matmul/gelu input except the attention score+prob tensors.
_SAVED_NAMES = (
    "ln1_out", "qkv", "attn_out_in", "resid_attn", "ln2_out",
    "pre_gelu", "gelu_out",
)


def _layer_norm(x, g, b, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps) * g + b


def backbone(cfg: GPT2Config, params: Dict, tokens: jax.Array,
             mesh=None) -> jax.Array:
    """tokens [B, T] int32 -> final hidden states [B, T, embd] (compute
    dtype), i.e. everything up to (not including) the lm-head matmul."""
    B, T = tokens.shape
    x = params["wte"].astype(cfg.dtype)[tokens] + params["wpe"].astype(cfg.dtype)[:T]

    blocks = params["blocks"]

    def _make_one(layer_params):
        # layer_params: one layer's slice of every block param
        from jax.ad_checkpoint import checkpoint_name

        def one(cfg_x):
            h = _layer_norm(
                cfg_x,
                layer_params["ln1_g"].astype(cfg.dtype),
                layer_params["ln1_b"].astype(cfg.dtype),
            )
            h = checkpoint_name(h, "ln1_out")
            B_, T_, E = cfg_x.shape
            qkv = h @ layer_params["attn_qkv_w"].astype(cfg.dtype) + layer_params[
                "attn_qkv_b"
            ].astype(cfg.dtype)
            qkv = checkpoint_name(qkv, "qkv")
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(B_, T_, cfg.n_head, cfg.head_dim)
            k = k.reshape(B_, T_, cfg.n_head, cfg.head_dim)
            v = v.reshape(B_, T_, cfg.n_head, cfg.head_dim)
            o = select_attention(cfg.attention, q, k, v, mesh, causal=True)
            o = checkpoint_name(o.reshape(B_, T_, E), "attn_out_in")
            x1 = cfg_x + (
                o @ layer_params["attn_out_w"].astype(cfg.dtype)
                + layer_params["attn_out_b"].astype(cfg.dtype)
            )
            x1 = checkpoint_name(x1, "resid_attn")
            h2 = _layer_norm(
                x1,
                layer_params["ln2_g"].astype(cfg.dtype),
                layer_params["ln2_b"].astype(cfg.dtype),
            )
            h2 = checkpoint_name(h2, "ln2_out")
            h2 = h2 @ layer_params["mlp_fc_w"].astype(cfg.dtype) + layer_params[
                "mlp_fc_b"
            ].astype(cfg.dtype)
            h2 = checkpoint_name(h2, "pre_gelu")
            h2 = jax.nn.gelu(h2)
            h2 = checkpoint_name(h2, "gelu_out")
            h2 = h2 @ layer_params["mlp_out_w"].astype(cfg.dtype) + layer_params[
                "mlp_out_b"
            ].astype(cfg.dtype)
            return x1 + h2

        return one

    def body(x, layer_params):
        one = _make_one(layer_params)
        if cfg.remat:
            if cfg.remat_policy == "dots":
                fn = jax.checkpoint(
                    one,
                    policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                )
            elif cfg.remat_policy == "names":
                fn = jax.checkpoint(
                    one,
                    policy=jax.checkpoint_policies.save_only_these_names(
                        *_SAVED_NAMES
                    ),
                )
            else:
                fn = jax.checkpoint(one)
        else:
            fn = one
        return fn(x), None

    def body_pair(x, pair_params):
        # remat_policy="half": checkpoint only the FIRST of each layer
        # pair — halves the backward's recompute FLOPs for half the
        # activation memory of no-remat (the sweet spot when full
        # activations OOM but full recompute wastes ~2N FLOPs/token)
        p0 = jax.tree.map(lambda a: a[0], pair_params)
        p1 = jax.tree.map(lambda a: a[1], pair_params)
        x = jax.checkpoint(_make_one(p0))(x)
        return _make_one(p1)(x), None

    def body_plain(x, layer_params):
        return _make_one(layer_params)(x), None

    x = x.astype(cfg.dtype)
    if cfg.remat and cfg.remat_policy == "half":
        if cfg.n_layer % 2:
            raise ValueError("remat_policy='half' needs an even n_layer")
        pairs = jax.tree.map(
            lambda a: a.reshape(cfg.n_layer // 2, 2, *a.shape[1:]), blocks
        )
        x, _ = lax.scan(body_pair, x, pairs, unroll=cfg.scan_unroll)
    elif cfg.remat and cfg.remat_skip:
        # two scans: the first (n_layer - remat_skip) layers remat, the
        # last remat_skip keep their activations and skip the backward
        # forward-replay entirely
        split = cfg.n_layer - cfg.remat_skip
        first = jax.tree.map(lambda a: a[:split], blocks)
        last = jax.tree.map(lambda a: a[split:], blocks)
        if split:
            x, _ = lax.scan(body, x, first, unroll=cfg.scan_unroll)
        x, _ = lax.scan(body_plain, x, last, unroll=cfg.scan_unroll)
    else:
        x, _ = lax.scan(body, x, blocks, unroll=cfg.scan_unroll)
    return _layer_norm(
        x, params["lnf_g"].astype(cfg.dtype), params["lnf_b"].astype(cfg.dtype)
    )


def lm_head(cfg: GPT2Config, params: Dict, x: jax.Array,
            out_dtype=jnp.float32) -> jax.Array:
    """Weight-tied projection to vocab logits — the ONE definition both
    the training loss and inference share."""
    return (x @ params["wte"].astype(cfg.dtype).T).astype(out_dtype)


def forward(cfg: GPT2Config, params: Dict, tokens: jax.Array,
            mesh=None) -> jax.Array:
    """tokens [B, T] int32 -> logits [B, T, vocab] (f32)."""
    return lm_head(cfg, params, backbone(cfg, params, tokens, mesh))


def loss_fn(cfg: GPT2Config, params: Dict, tokens: jax.Array,
            mesh=None) -> jax.Array:
    """Next-token cross entropy; tokens [B, T+1] (shift done here).

    Uses the lse-reduction form: XLA fuses the logsumexp into the
    lm-head matmul's epilogue, so the [B, T, vocab] *log-prob* tensor
    never materializes (the logits do, transiently).  Measured best at
    EVERY scale tried on v5e (PERF.md r5): the lm-head is MXU-bound at
    these widths, XLA stores bf16 logits once and skips the backward
    recompute, and its fused schedule keeps scaling linearly even when
    logits+dlogits exceed HBM — so both no-materialize formulations
    (`ops.xent.fused_cross_entropy` scan-chunked, and the Pallas
    blockwise `ops.xent_pallas.pallas_cross_entropy`) lose: they must
    recompute the lm-head matmul in the backward, which costs more
    than the HBM they save.
    """
    inputs = tokens[:, :-1]
    targets = tokens[:, 1:]
    x = backbone(cfg, params, inputs, mesh)
    logits = lm_head(cfg, params, x, out_dtype=cfg.logits_dtype)
    # reductions in f32 regardless of the logits' storage dtype (XLA
    # fuses the upcast into the reduce: no f32 materialization)
    lse = jax.scipy.special.logsumexp(
        logits.astype(jnp.float32), axis=-1
    )
    tgt = jnp.take_along_axis(
        logits, targets[..., None], axis=-1
    )[..., 0].astype(jnp.float32)
    return jnp.mean(lse - tgt)


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))


# ----------------------------------------------------------------------
# train step
# ----------------------------------------------------------------------
def make_train_step(cfg: GPT2Config, optimizer, mesh=None):
    """Returns step(params, opt_state, tokens) -> (params, opt_state,
    metrics).  Pure; callers jit it with shardings."""

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(cfg, p, tokens, mesh)
        )(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u, params, updates)
        gnorm = jnp.sqrt(
            sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in jax.tree.leaves(grads))
        )
        return params, opt_state, {"loss": loss, "grad_norm": gnorm}

    return step


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup_steps: int = 100, total_steps: int = 10_000):
    import optax

    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1)
    )
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )
