"""Metric primitives + the per-process registry.

Reference: `python/ray/util/metrics.py` (the user-facing Counter /
Gauge / Histogram) over `src/ray/stats/metric.h` (the tagged metric
core).  Every process — driver, node daemon, worker — holds ONE
registry; `snapshot()` freezes it into plain data that travels the
control plane (the batched obs frames `core/runtime.py` /
`core/noded.py` ship to the controller), and `render_exposition()`
turns any pile of snapshots — local or collected cluster-wide — into
Prometheus text exposition.  The split is what lets the dashboard head
serve one merged `/metrics` for the whole cluster without a per-sample
RPC anywhere.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_registry_lock = threading.Lock()
_registry: List["Metric"] = []


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.append(self)

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merge(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged

    def _samples(self) -> List[Tuple[Dict[str, str], float]]:
        raise NotImplementedError

    def _type(self) -> str:
        raise NotImplementedError


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = _label_key(self._merge(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def _samples(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def _type(self):
        return "counter"


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        with self._lock:
            self._values[_label_key(self._merge(tags))] = float(value)

    def clear(self):
        """Drop all tagged series — refresh-style exporters that
        recompute the full tag set each pass call this first so
        vanished tag values (a deleted app, a drained state) stop
        exporting stale samples."""
        with self._lock:
            self._values.clear()

    def _samples(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    def _type(self):
        return "gauge"


class Histogram(Metric):
    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [0.1, 1, 10, 100]
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _label_key(self._merge(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1)
            )
            self._sums[key] = self._sums.get(key, 0.0) + value
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    return
            counts[-1] += 1

    def _samples(self):
        out = []
        with self._lock:
            for key, counts in self._counts.items():
                labels = dict(key)
                cum = 0
                for b, c in zip(self.boundaries, counts):
                    cum += c
                    out.append(({**labels, "le": str(b)}, float(cum)))
                cum += counts[-1]
                out.append(({**labels, "le": "+Inf"}, float(cum)))
                out.append(({**labels, "__count__": "1"}, float(cum)))
                out.append(({**labels, "__sum__": "1"}, self._sums[key]))
        return out

    def _type(self):
        return "histogram"


# ----------------------------------------------------------------------
# snapshot / exposition
# ----------------------------------------------------------------------
def snapshot(extra_tags: Optional[Dict[str, str]] = None) -> List[Dict]:
    """Freeze the registry into plain wire-encodable data: one dict per
    metric — `{"name", "type", "help", "samples": [[labels, value]]}` —
    with histogram samples in the marker form `_samples()` emits.
    `extra_tags` (e.g. node/proc identity) fold into every sample's
    labels so snapshots from many processes merge without collisions."""
    with _registry_lock:
        metrics = list(_registry)
    out: List[Dict] = []
    for m in metrics:
        samples = m._samples()
        if extra_tags:
            samples = [({**labels, **extra_tags}, v) for labels, v in samples]
        out.append({
            "name": m.name,
            "type": m._type(),
            "help": m.description,
            "samples": [[labels, v] for labels, v in samples],
        })
    return out


def _sample_lines(name: str, samples) -> List[str]:
    lines = []
    for labels, value in samples:
        labels = dict(labels)
        if labels.pop("__sum__", None) is not None:
            sname = f"{name}_sum"
        elif labels.pop("__count__", None) is not None:
            sname = f"{name}_count"
        elif "le" in labels:
            sname = f"{name}_bucket"
        else:
            sname = name
        if labels:
            inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            lines.append(f"{sname}{{{inner}}} {value}")
        else:
            lines.append(f"{sname} {value}")
    return lines


def render_exposition(metric_snapshots: Sequence[Dict]) -> str:
    """Prometheus text exposition over any collection of metric
    snapshots (local and/or collected from other processes).  Snapshots
    sharing a name merge under one HELP/TYPE header — exposition
    requires each metric family to appear exactly once."""
    by_name: Dict[str, Dict] = {}
    order: List[str] = []
    for snap in metric_snapshots:
        name = snap["name"]
        ent = by_name.get(name)
        if ent is None:
            ent = by_name[name] = {
                "type": snap.get("type", "gauge"),
                "help": snap.get("help", ""),
                "samples": [],
            }
            order.append(name)
        ent["samples"].extend(snap.get("samples", ()))
    lines: List[str] = []
    for name in order:
        ent = by_name[name]
        if ent["help"]:
            lines.append(f"# HELP {name} {ent['help']}")
        lines.append(f"# TYPE {name} {ent['type']}")
        lines.extend(_sample_lines(name, ent["samples"]))
    return "\n".join(lines) + "\n"


def export_text() -> str:
    """Prometheus text exposition of this process's registry."""
    return render_exposition(snapshot())
