"""Cluster metrics collection: batched frames, controller-side sink.

Reference: the per-node metrics agent + dashboard aggregation
(`dashboard/modules/metrics/`, `src/ray/stats/`) — every process
exports its registry periodically, an aggregator keys the snapshots by
reporter, and one scrape endpoint serves the merged view.

The shipping here rides paths that already exist (the same discipline
as PR 7's `ResultCoalescer`): drivers and workers attach their registry
snapshot to the periodic task-event flush frame, node daemons ship one
`report_obs` frame per interval on their controller connection — ONE
frame per process per interval, NEVER a per-sample RPC.  The controller
keeps only the LATEST snapshot per reporter (metrics are level-based;
counters are cumulative in the reporting process), so a hot worker
cannot grow controller memory: the sink is bounded by live reporters
and expires the dead ones by wall age.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.metrics import registry as _registry

# a reporter silent this long is presumed dead and its series vanish
# from the merged exposition (matches prometheus staleness handling)
REPORTER_TTL_S = 30.0


def collect_frame(node_id: str, kind: str, pid: int) -> Optional[Dict]:
    """This process's registry as one wire-ready obs frame; None when
    the registry holds no samples (nothing to ship, no empty frames on
    the wire)."""
    snap = _registry.snapshot()
    if not any(m["samples"] for m in snap):
        return None
    return {
        "node_id": node_id,
        "kind": kind,
        "pid": int(pid),
        "metrics": snap,
    }


def build_obs_payload(node_id: str, kind: str, pid: int,
                      refresh: Optional[Callable[[], None]] = None
                      ) -> Optional[Dict]:
    """THE `report_obs` frame shape, built in one place for every
    reporter kind (driver/worker flush loop in `core/runtime.py`, the
    daemon loop in `core/noded.py`): drained spans + this process's
    registry snapshot, or None when both planes have nothing.
    `refresh` runs scrape-time gauge updates (the daemon's store
    levels) only when metrics are actually on.  Callers must check
    their connection BEFORE calling: a drained span that cannot be
    sent is silently lost, while one left in the export queue is
    either shipped next tick or counted as dropped."""
    from ray_tpu.metrics import metric_defs as _md
    from ray_tpu.util import tracing as _tracing

    spans = _tracing.drain_export() if _tracing.is_enabled() else []
    metrics_snap = None
    if _md.enabled():
        if refresh is not None:
            refresh()
        frame = collect_frame(node_id, kind, pid)
        if frame is not None:
            metrics_snap = frame["metrics"]
    if not spans and metrics_snap is None:
        return None
    payload: Dict = {"node_id": node_id, "kind": kind, "pid": int(pid)}
    if metrics_snap is not None:
        payload["metrics"] = metrics_snap
    if spans:
        payload["spans"] = spans
    return payload


class MetricsSink:
    """Controller-side collection: latest snapshot per reporter.

    Single-threaded by construction — every touch happens inside
    controller handlers on the controller's io loop, so no lock."""

    def __init__(self, ttl_s: float = REPORTER_TTL_S):
        self.ttl_s = ttl_s
        # (node_id, kind, pid) -> (wall_ts, [metric snapshots])
        self._by_reporter: Dict[Tuple[str, str, int], Tuple[float, List]] = {}

    def _purge(self, now: float):
        dead = [k for k, (ts, _) in self._by_reporter.items()
                if now - ts > self.ttl_s]
        for k in dead:
            del self._by_reporter[k]

    def ingest(self, frame: Dict):
        now = time.time()
        # purge on the WRITE path too: with no scraper, reporter churn
        # (new jobs, respawned workers) would otherwise grow this dict
        # without bound — merged() alone only purges when someone reads
        self._purge(now)
        key = (
            str(frame.get("node_id", "")),
            str(frame.get("kind", "")),
            int(frame.get("pid", 0)),
        )
        self._by_reporter[key] = (now, frame.get("metrics") or [])

    def merged(self) -> List[Dict]:
        """Snapshots from every live reporter, each sample tagged with
        its origin (`node`, `proc`) so series from different processes
        stay distinct in the merged exposition."""
        self._purge(time.time())
        out: List[Dict] = []
        for (node_id, kind, pid), (_, snaps) in self._by_reporter.items():
            origin = {"node": node_id[:8], "proc": f"{kind}:{pid}"}
            for m in snaps:
                out.append({
                    "name": m.get("name", ""),
                    "type": m.get("type", "gauge"),
                    "help": m.get("help", ""),
                    "samples": [
                        [{**(labels or {}), **origin}, value]
                        for labels, value in m.get("samples", ())
                    ],
                })
        return out

    def reporter_count(self) -> int:
        return len(self._by_reporter)
