"""Unified metrics plane: tagged primitives, a central catalog, and
cluster-wide collection.

- :mod:`ray_tpu.metrics.registry` — Counter / Gauge / Histogram and the
  per-process registry (`snapshot()` / `render_exposition()` /
  `export_text()`).
- :mod:`ray_tpu.metrics.metric_defs` — the `metric_defs.h`-analogue
  catalog of every core metric name, plus the gated `inc/observe/
  set_gauge` helpers the hot subsystems call.
- :mod:`ray_tpu.metrics.exporter` — batched frame collection and the
  controller-side :class:`MetricsSink` behind the dashboard's merged
  `/metrics`.

User code keeps importing the primitives from `ray_tpu.util.metrics`
(the reference's path); that module is a re-export of the registry.
"""

from ray_tpu.metrics.metric_defs import (
    CATALOG,
    enabled,
    inc,
    metric,
    observe,
    set_enabled,
    set_gauge,
)
from ray_tpu.metrics.registry import (
    Counter,
    Gauge,
    Histogram,
    Metric,
    export_text,
    render_exposition,
    snapshot,
)

__all__ = [
    "CATALOG",
    "Counter",
    "Gauge",
    "Histogram",
    "Metric",
    "enabled",
    "export_text",
    "inc",
    "metric",
    "observe",
    "render_exposition",
    "set_enabled",
    "set_gauge",
    "snapshot",
]
