"""Central metric-definitions catalog.

Reference: `src/ray/stats/metric_defs.h` — every core metric the system
emits is declared ONCE, in one table, with its type, help string, tag
keys, and (histograms) bucket boundaries.  Subsystems never invent
ad-hoc names: they call :func:`inc` / :func:`observe` / :func:`set_gauge`
with a cataloged name, and the accessor lazily instantiates the metric
in this process's registry on first touch.

Hot-path discipline: core instrumentation is OFF by default
(`RT_METRICS_ENABLED` / `Config.metrics_enabled`).  The record helpers
check one module flag and return — a disabled record costs a function
call and a bool test, which is what keeps the measured task-storm
overhead of the whole plane under the 3% budget (`perf.py --config
obs_overhead`, PERF.md).  Scrape-time refreshes (the dashboard's
builtin gauges, the serve stats bridge) bypass the gate — they run per
scrape, never per task.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ray_tpu.metrics.registry import Counter, Gauge, Histogram, Metric

# latency buckets: control-plane ops span ~100 us (owner hot path) to
# tens of seconds (lease negotiation against a saturated daemon)
_LATENCY_S = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
# coarse work-unit buckets (shuffle partitions, train steps)
_WORK_S = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
           60.0, 300.0)

# name -> (type, help, tag_keys, boundaries-or-None)
CATALOG: Dict[str, Tuple[str, str, Tuple[str, ...], Optional[tuple]]] = {
    # ---- owner plane (core/runtime.py, core/completion.py) ----------
    "rt_owner_tasks_submitted_total": (
        "counter", "tasks registered with the owner plane", ("shard",),
        None),
    "rt_owner_tasks_completed_total": (
        "counter", "owner-side final task completions",
        ("shard", "outcome"), None),
    "rt_owner_task_retries_total": (
        "counter", "owner-side task retry resubmissions", ("shard",),
        None),
    "rt_owner_task_latency_seconds": (
        "histogram", "submit-to-final-completion wall latency",
        ("shard",), _LATENCY_S),
    "rt_owner_lease_latency_seconds": (
        "histogram", "request_lease round-trip against the node daemon",
        ("shard",), _LATENCY_S),
    "rt_owner_lease_grants_total": (
        "counter", "worker lease grants adopted", ("shard",), None),
    # ---- task-event feed (core/task_events.py) ----------------------
    "rt_task_events_dropped_total": (
        "counter", "task events dropped at the full buffer", (), None),
    # ---- object plane (core/noded.py, core/runtime.py) --------------
    "rt_object_store_used_bytes": (
        "gauge", "object store bytes in use", (), None),
    "rt_object_store_capacity_bytes": (
        "gauge", "object store capacity", (), None),
    "rt_object_store_objects": (
        "gauge", "sealed objects resident in the store", (), None),
    "rt_object_spilled_objects": (
        "gauge", "primary copies currently spilled to disk", (), None),
    "rt_object_spill_bytes_total": (
        "counter", "bytes spilled to disk (monotonic)", (), None),
    "rt_object_restore_bytes_total": (
        "counter", "bytes restored from disk (monotonic)", (), None),
    "rt_object_reconstructions_total": (
        "counter", "lost objects re-derived via lineage resubmit", (),
        None),
    # ---- object integrity + storage faults (core/noded.py,
    # core/diskio.py; these record rare FAILURE events, so their
    # call sites bypass the metrics_enabled gate) --------------------
    "rt_object_integrity_errors_total": (
        "counter", "checksum verification failures by path "
        "(restore | transfer | get | snapshot)", ("path",), None),
    "rt_object_quarantined_total": (
        "counter", "corrupt spilled files moved to quarantine", (),
        None),
    "rt_spill_disk_full_total": (
        "counter", "spill passes refused by the low-disk watermark or "
        "aborted by ENOSPC", (), None),
    "rt_spill_errors_total": (
        "counter", "disk I/O errors on the spill plane by op "
        "(spill | restore)", ("op",), None),
    # ---- shuffle (data/shuffle.py) ----------------------------------
    "rt_shuffle_partition_seconds": (
        "histogram", "wall time of one shuffle map/reduce task "
        "(admission to completion)", ("phase",), _WORK_S),
    "rt_shuffle_backpressure_total": (
        "counter", "shuffle admission stalls raised as "
        "BackPressureError", ("phase",), None),
    "rt_shuffle_rows_total": (
        "counter", "rows entering the shuffle map phase", (), None),
    # ---- serve (bridged from engine/replica stats(), scrape-time) ---
    "rt_serve_engine_queue_depth": (
        "gauge", "engine queue depth (active + queued + pending "
        "admissions)", ("app", "deployment", "replica"), None),
    "rt_serve_engine_block_occupancy": (
        "gauge", "KV block pool occupancy fraction",
        ("app", "deployment", "replica"), None),
    "rt_serve_engine_prefix_hit_rate": (
        "gauge", "radix prefix cache hit rate over served tokens",
        ("app", "deployment", "replica"), None),
    "rt_serve_engine_ttft_ema_seconds": (
        "gauge", "time-to-first-token EMA",
        ("app", "deployment", "replica"), None),
    "rt_serve_engine_ttft_p90_seconds": (
        "gauge", "windowed time-to-first-token p90 (decays; feeds "
        "shedding + SLO autoscaling)",
        ("app", "deployment", "replica"), None),
    "rt_serve_engine_rejected_total": (
        "gauge", "engine admission rejections (monotonic, bridged)",
        ("app", "deployment", "replica"), None),
    "rt_serve_engine_shed_total": (
        "gauge", "deadline sheds before prefill (monotonic, bridged)",
        ("app", "deployment", "replica"), None),
    "rt_serve_kv_pool_bytes": (
        "gauge", "resident KV block-pool payload bytes (K+V, "
        "excluding the int8 f32 scale sidecar)",
        ("app", "deployment", "replica"), None),
    "rt_serve_decode_kernel_total": (
        "gauge", "decode ticks dispatched through the fused paged-"
        "attention kernel (monotonic, bridged; gather-fallback ticks "
        "are the engine's decode_fallback_dispatch_total)",
        ("app", "deployment", "replica"), None),
    # ---- serve request ledger (serve/request_ledger.py; windowed
    # per-request phase latencies replacing EMA-only reporting) -------
    "rt_serve_ttft_seconds": (
        "histogram", "request time-to-first-token (submit to first "
        "harvested token)", ("app", "deployment", "replica"),
        _LATENCY_S),
    "rt_serve_tpot_seconds": (
        "histogram", "mean time per output token after the first "
        "(decode cadence)", ("app", "deployment", "replica"),
        _LATENCY_S),
    "rt_serve_queue_wait_seconds": (
        "histogram", "router assignment wait (request arrival to "
        "replica pick)", ("app", "deployment", "replica"), _LATENCY_S),
    "rt_serve_prefill_seconds": (
        "histogram", "engine prefill wall time (admission to KV "
        "residency)", ("app", "deployment", "replica"), _LATENCY_S),
    "rt_serve_e2e_seconds": (
        "histogram", "end-to-end request latency at the ledger origin "
        "(proxy arrival or replica entry to terminal phase)",
        ("app", "deployment", "replica"), _LATENCY_S),
    # ---- rllib (rllib/env/env_runner_group.py, algorithms/ppo.py) ---
    "rt_rllib_env_steps_total": (
        "counter", "env steps consumed by the learner side (ledger-"
        "recorded, exactly once per sample batch)", (), None),
    "rt_rllib_sample_batch_bytes_total": (
        "counter", "sample-batch payload bytes fetched from the object "
        "plane", (), None),
    "rt_rllib_learner_update_seconds": (
        "histogram", "wall time of one full learner update pass "
        "(all epochs over one train batch)", (), _WORK_S),
    "rt_rllib_env_runners": (
        "gauge", "env-runner fleet size (replacements keep it at "
        "target; 0 after stop)", (), None),
    # ---- compiled DAGs (dag/execution.py, dag/channel.py) -----------
    "rt_dag_execs_total": (
        "counter", "completed executions per resident exec loop "
        "(one inc per full pass over the actor's compiled steps)", (),
        None),
    "rt_dag_channel_write_seconds": (
        "histogram", "wall time of one channel slot publication "
        "(acquire + copy + seal; includes the spill put for oversized "
        "payloads and the daemon relay for cross-node writes)", (),
        _LATENCY_S),
    "rt_dag_channel_ring_full_total": (
        "counter", "channel writes that blocked on (or timed out "
        "against) a full ring — the reader is lagging more than "
        "dag_ring_slots messages behind", (), None),
    # ---- train (train/trainer.py) -----------------------------------
    "rt_train_step_seconds": (
        "histogram", "wall time between delivered training result "
        "rounds", (), _WORK_S),
    "rt_train_elastic_events_total": (
        "counter", "elastic lifecycle transitions (shrink / reform / "
        "regrow)", ("kind",), None),
    # ---- observability plane itself ---------------------------------
    "rt_obs_frames_sent_total": (
        "counter", "batched obs frames shipped to the controller", (),
        None),
    "rt_trace_spans_dropped_total": (
        "counter", "finished spans dropped at the full export queue",
        (), None),
}

_lock = threading.Lock()
_instances: Dict[str, Metric] = {}

# Core-path gate.  Read once from the environment at import (workers
# inherit RT_METRICS_ENABLED through the daemon spawn chain exactly
# like the tracing flag); flip at runtime with set_enabled().
_enabled = os.environ.get("RT_METRICS_ENABLED", "") in ("1", "true", "True")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool):
    """Flip core-path instrumentation for THIS process; also mirrors
    the env flag so children spawned after the flip inherit it."""
    global _enabled
    _enabled = bool(on)
    if on:
        os.environ["RT_METRICS_ENABLED"] = "1"
    else:
        os.environ.pop("RT_METRICS_ENABLED", None)


def metric(name: str) -> Metric:
    """The process-local instance of a cataloged metric (lazy,
    singleton).  Raises KeyError for names outside the catalog — the
    whole point is that core metric names exist in one table."""
    m = _instances.get(name)
    if m is not None:
        return m
    with _lock:
        m = _instances.get(name)
        if m is not None:
            return m
        typ, help_, tag_keys, boundaries = CATALOG[name]
        if typ == "counter":
            m = Counter(name, help_, tag_keys=tag_keys)
        elif typ == "gauge":
            m = Gauge(name, help_, tag_keys=tag_keys)
        else:
            m = Histogram(name, help_, boundaries=boundaries or (),
                          tag_keys=tag_keys)
        _instances[name] = m
        return m


# -- gated record helpers (the core hot paths call these) --------------
def inc(name: str, value: float = 1.0,
        tags: Optional[Dict[str, str]] = None):
    if not _enabled:
        return
    metric(name).inc(value, tags=tags)


def observe(name: str, value: float,
            tags: Optional[Dict[str, str]] = None):
    if not _enabled:
        return
    metric(name).observe(value, tags=tags)


def set_gauge(name: str, value: float,
              tags: Optional[Dict[str, str]] = None):
    if not _enabled:
        return
    metric(name).set(value, tags=tags)
