"""Grafana dashboard factory + built-in cluster metrics.

Reference: `dashboard/modules/metrics/grafana_dashboard_factory.py` —
Grafana dashboard JSON generated from declarative panel configs over the
metrics the cluster exports, so operators import one file instead of
hand-building boards.  `rt grafana-dashboard --out d/` and
`GET /api/grafana_dashboard` both emit it.

The built-in gauges mirror the reference's core `ray_*` series
(`src/ray/stats/metric_defs.h:46-120` — nodes/actors/scheduler/object
store) and are refreshed from controller state at scrape time by the
dashboard's `/metrics` handler.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.metrics import Gauge

logger = logging.getLogger(__name__)

# -- built-in cluster metrics -------------------------------------------
_builtin: Dict[str, Gauge] = {}


def _gauge(name: str, desc: str, tag_keys=()) -> Gauge:
    g = _builtin.get(name)
    if g is None:
        g = _builtin[name] = Gauge(name, desc, tag_keys=tag_keys)
    return g


async def update_builtin_metrics(ctl):
    """Refresh cluster gauges from controller state; `ctl(method,
    payload=None)` is the dashboard's controller-call coroutine."""
    nodes = await ctl("get_nodes") or []
    _gauge("rt_nodes", "cluster nodes by liveness", ("state",)).set(
        float(sum(1 for n in nodes if n["alive"])), {"state": "alive"}
    )
    _gauge("rt_nodes", "cluster nodes by liveness", ("state",)).set(
        float(sum(1 for n in nodes if not n["alive"])), {"state": "dead"}
    )
    actors = await ctl("list_actors") or []
    by_state: Dict[str, int] = {}
    for a in actors:
        by_state[a["state"]] = by_state.get(a["state"], 0) + 1
    g = _gauge("rt_actors", "actors by state", ("state",))
    g.clear()  # states with zero actors must stop exporting old counts
    for state, count in by_state.items():
        g.set(float(count), {"state": state})
    auto = await ctl("get_autoscaler_state") or {}
    _gauge("rt_pending_demands", "unscheduled resource demands").set(
        float(len(auto.get("pending_demands", [])))
    )
    _gauge("rt_pending_gangs", "unplaced placement groups").set(
        float(len(auto.get("pending_gangs", [])))
    )
    snap = await ctl("get_worker_snapshot")
    if snap is not None:
        _gauge("rt_workers", "live worker processes").set(float(len(snap)))
    # serve replica targets vs running, per (app, deployment)
    try:
        from ray_tpu.serve.api import _get_controller_async
        from ray_tpu.core.runtime import get_runtime

        controller = await _get_controller_async()
        ref = controller.get_serve_status.remote()
        status = await get_runtime()._get_one(ref)
    except Exception:
        status = {}
    g = _gauge("rt_serve_replicas", "serve replicas",
               ("app", "deployment", "kind"))
    g.clear()  # deleted apps/deployments must not export stale series
    req = _gauge("rt_serve_requests_total",
                 "completed serve requests (monotonic)",
                 ("app", "deployment"))
    lat = _gauge("rt_serve_latency_seconds_sum",
                 "summed serve request latency (monotonic)",
                 ("app", "deployment"))
    req.clear()
    lat.clear()
    # LLM-engine panel bridge: the per-replica stats() piggyback the
    # controller already collects (queue depth, pool occupancy, radix
    # hit rate, shed/reject counters) re-exported under the CATALOGED
    # names (`ray_tpu/metrics/metric_defs.py`) — the registry view and
    # /api/serve stay one source of truth, nothing is double-polled
    from ray_tpu.metrics import metric_defs as _mdefs

    _ENGINE_BRIDGE = {
        "rt_serve_engine_queue_depth": "queue_depth",
        "rt_serve_engine_block_occupancy": "block_occupancy",
        "rt_serve_engine_prefix_hit_rate": "prefix_hit_rate",
        "rt_serve_engine_ttft_ema_seconds": "ttft_ema_s",
        "rt_serve_engine_ttft_p90_seconds": "ttft_p90_s",
        "rt_serve_engine_rejected_total": "rejected_total",
        "rt_serve_engine_shed_total": "shed_total",
        "rt_serve_kv_pool_bytes": "kv_pool_bytes",
        "rt_serve_decode_kernel_total": "decode_kernel_dispatch_total",
    }
    eng_gauges = {name: _mdefs.metric(name) for name in _ENGINE_BRIDGE}
    for eg in eng_gauges.values():
        eg.clear()  # dead replicas must not export stale series
    for app, deployments in (status or {}).items():
        for dep, info in deployments.items():
            tags = {"app": app, "deployment": dep}
            g.set(float(info.get("running", 0)), {**tags, "kind": "running"})
            g.set(float(info.get("target_replicas", 0)),
                  {**tags, "kind": "target"})
            req.set(float(info.get("completed", 0.0)), tags)
            lat.set(float(info.get("latency_sum_s", 0.0)), tags)
            for rid, rinfo in (info.get("replicas") or {}).items():
                engine = rinfo.get("engine")
                if not isinstance(engine, dict):
                    continue
                rtags = {**tags, "replica": rid}
                for mname, skey in _ENGINE_BRIDGE.items():
                    try:
                        eng_gauges[mname].set(float(engine.get(skey, 0.0)),
                                              rtags)
                    except (TypeError, ValueError):
                        logger.debug("engine stat %s=%r not numeric",
                                     skey, engine.get(skey))
    # per-replica series (reference: `serve/metrics.py` replica-tagged
    # request counter / queue gauge / latency histogram) so autoscaling
    # decisions are auditable from /metrics
    try:
        ref = controller.get_replica_metrics.remote()
        per_replica = await get_runtime()._get_one(ref)
    except Exception:
        per_replica = {}
    rep_tags = ("app", "deployment", "replica")
    rr = _gauge("rt_serve_replica_requests_total",
                "completed requests per replica (monotonic)", rep_tags)
    rq = _gauge("rt_serve_replica_queue_depth",
                "in-flight requests per replica", rep_tags)
    rls = _gauge("rt_serve_replica_latency_seconds_sum",
                 "summed request latency per replica", rep_tags)
    rlb = _gauge("rt_serve_replica_latency_seconds_bucket",
                 "request latency histogram per replica",
                 rep_tags + ("le",))
    for m in (rr, rq, rls, rlb):
        m.clear()  # dead replicas must not export stale series
    from ray_tpu.serve.replica import LATENCY_BOUNDARIES

    for app, deployments in (per_replica or {}).items():
        for dep, replicas in deployments.items():
            for rid, m in replicas.items():
                tags = {"app": app, "deployment": dep, "replica": rid}
                # COMPLETED requests: the histogram count basis (the
                # started-count would put phantom in-flight mass in the
                # +Inf bucket and wreck histogram_quantile)
                completed = float(m.get("completed", m.get("total", 0)))
                rr.set(completed, tags)
                rq.set(float(m.get("ongoing", 0)), tags)
                rls.set(float(m.get("latency_sum_s", 0.0)), tags)
                buckets = m.get("latency_buckets") or []
                cum = 0.0
                for bound, n in zip(LATENCY_BOUNDARIES, buckets):
                    cum += n
                    rlb.set(cum, {**tags, "le": str(bound)})
                rlb.set(completed, {**tags, "le": "+Inf"})


# -- dashboard generation -----------------------------------------------
@dataclass
class Target:
    expr: str
    legend: str = ""


@dataclass
class Panel:
    title: str
    unit: str = "short"
    targets: List[Target] = field(default_factory=list)
    description: str = ""


DEFAULT_PANELS: List[Panel] = [
    Panel("Alive nodes", targets=[Target('rt_nodes{state="alive"}', "alive"),
                                  Target('rt_nodes{state="dead"}', "dead")]),
    Panel("Actors by state",
          targets=[Target("rt_actors", "{{state}}")]),
    Panel("Live workers", targets=[Target("rt_workers", "workers")]),
    Panel("Pending resource demands",
          targets=[Target("rt_pending_demands", "demands"),
                   Target("rt_pending_gangs", "gangs")],
          description="nonzero sustained = cluster needs to scale up"),
    Panel("Serve replicas: running vs target",
          targets=[Target('rt_serve_replicas{kind="running"}',
                          "{{app}}/{{deployment}} running"),
                   Target('rt_serve_replicas{kind="target"}',
                          "{{app}}/{{deployment}} target")],
          description="running < target sustained = replicas failing "
                      "to start"),
    Panel("Serve request rate", unit="reqps",
          targets=[Target("rate(rt_serve_requests_total[1m])",
                          "{{app}}/{{deployment}}")]),
    Panel("Serve mean latency", unit="s",
          targets=[Target(
              "rate(rt_serve_latency_seconds_sum[5m]) / "
              "rate(rt_serve_requests_total[5m])",
              "{{app}}/{{deployment}}")]),
    # ---- unified observability plane (ray_tpu/metrics catalog) ------
    Panel("Task throughput", unit="ops",
          targets=[Target(
              "sum by (shard) (rate(rt_owner_tasks_completed_total[1m]))",
              "shard {{shard}}")],
          description="owner-plane completions/s per shard "
                      "(RT_METRICS_ENABLED=1)"),
    Panel("Task latency p99", unit="s",
          targets=[Target(
              "histogram_quantile(0.99, sum by (le) "
              "(rate(rt_owner_task_latency_seconds_bucket[5m])))",
              "p99")],
          description="submit to final completion, owner-side"),
    Panel("Object store occupancy", unit="bytes",
          targets=[Target("rt_object_store_used_bytes", "{{node}} used"),
                   Target("rt_object_store_capacity_bytes",
                          "{{node}} capacity")]),
    Panel("Spill / restore rate", unit="Bps",
          targets=[Target("rate(rt_object_spill_bytes_total[5m])",
                          "{{node}} spill"),
                   Target("rate(rt_object_restore_bytes_total[5m])",
                          "{{node}} restore")]),
    Panel("Object integrity + storage faults",
          targets=[Target("rate(rt_object_integrity_errors_total[5m])",
                          "checksum failures {{path}}"),
                   Target("rate(rt_object_quarantined_total[5m])",
                          "quarantined spill files"),
                   Target("rate(rt_spill_disk_full_total[5m])",
                          "spill disk full"),
                   Target("rate(rt_spill_errors_total[5m])",
                          "disk I/O errors {{op}}")],
          description="any nonzero = a disk is corrupting or refusing "
                      "data; jobs survive via quarantine + lineage, "
                      "but the device needs attention"),
    Panel("Shuffle backpressure + reconstructions",
          targets=[Target("rate(rt_shuffle_backpressure_total[5m])",
                          "backpressure {{phase}}"),
                   Target("rate(rt_object_reconstructions_total[5m])",
                          "lineage reconstructions")],
          description="sustained nonzero = store budget or partition "
                      "count needs tuning"),
    # ---- serve request ledger (serve/request_ledger.py) -------------
    Panel("Serve request latency", unit="s",
          targets=[Target(
              "histogram_quantile(0.9, sum by (le, app, deployment) "
              "(rate(rt_serve_ttft_seconds_bucket[5m])))",
              "ttft p90 {{app}}/{{deployment}}"),
              Target(
              "histogram_quantile(0.9, sum by (le, app, deployment) "
              "(rate(rt_serve_e2e_seconds_bucket[5m])))",
              "e2e p90 {{app}}/{{deployment}}"),
              Target(
              "histogram_quantile(0.9, sum by (le, app, deployment) "
              "(rate(rt_serve_queue_wait_seconds_bucket[5m])))",
              "queue wait p90 {{app}}/{{deployment}}")],
          description="per-request ledger phases (windowed histograms, "
                      "not EMAs): TTFT, end-to-end, and router queue "
                      "wait; pair with /api/slo burn rates"),
    Panel("Serve decode cadence", unit="s",
          targets=[Target(
              "histogram_quantile(0.5, sum by (le, app, deployment) "
              "(rate(rt_serve_tpot_seconds_bucket[5m])))",
              "tpot p50 {{app}}/{{deployment}}"),
              Target(
              "histogram_quantile(0.9, sum by (le, app, deployment) "
              "(rate(rt_serve_prefill_seconds_bucket[5m])))",
              "prefill p90 {{app}}/{{deployment}}")],
          description="time-per-output-token and prefill from the "
                      "engine tickets on the request ledger"),
    Panel("Engine queue depth",
          targets=[Target("rt_serve_engine_queue_depth",
                          "{{app}}/{{deployment}}/{{replica}}")],
          description="bridged from the replicas' stats() piggyback"),
    Panel("Engine KV pool + decode kernel", unit="bytes",
          targets=[Target("rt_serve_kv_pool_bytes",
                          "pool {{app}}/{{deployment}}/{{replica}}"),
                   Target("rate(rt_serve_decode_kernel_total[5m])",
                          "kernel ticks/s "
                          "{{app}}/{{deployment}}/{{replica}}")],
          description="int8 pools sit at half the fp16 payload bytes; "
                      "a zero kernel rate on TPU means the engine fell "
                      "back to the gather decode route"),
    Panel("Train step time p50", unit="s",
          targets=[Target(
              "histogram_quantile(0.5, sum by (le) "
              "(rate(rt_train_step_seconds_bucket[5m])))", "p50")]),
    Panel("RLlib fleet throughput",
          targets=[Target("rate(rt_rllib_env_steps_total[1m])",
                          "env steps/s"),
                   Target("rate(rt_rllib_sample_batch_bytes_total[1m])",
                          "sample bytes/s"),
                   Target("rt_rllib_env_runners", "env runners")],
          description="EnvRunner fleet → learner gang: consumed env "
                      "steps (exactly-once ledger), object-plane "
                      "sample bytes, and fleet size (dips = runner "
                      "replacements in progress)"),
    Panel("RLlib learner update p50", unit="s",
          targets=[Target(
              "histogram_quantile(0.5, sum by (le) "
              "(rate(rt_rllib_learner_update_seconds_bucket[5m])))",
              "p50")],
          description="full epochs pass over one train batch; compare "
                      "against sample_busy_s for the overlap budget"),
    Panel("Compiled-DAG fast plane",
          targets=[Target("rate(rt_dag_execs_total[1m])",
                          "exec-loop executions/s"),
                   Target("rate(rt_dag_channel_ring_full_total[5m])",
                          "ring-full writes"),
                   Target(
                       "histogram_quantile(0.99, sum by (le) "
                       "(rate(rt_dag_channel_write_seconds_bucket[5m])))",
                       "channel write p99")],
          description="resident exec loops + shm tensor channels; "
                      "sustained ring-full = a reader is the "
                      "bottleneck (raise RT_DAG_RING_SLOTS or fix the "
                      "slow stage)"),
    Panel("Dropped task events",
          targets=[Target("rate(rt_task_events_dropped_total[5m])",
                          "{{proc}}")],
          description="nonzero = the event flush cannot keep up; "
                      "raise RT_TASK_EVENTS_BUFFER_SIZE"),
]


def _panel_json(p: Panel, panel_id: int, x: int, y: int) -> Dict[str, Any]:
    return {
        "id": panel_id,
        "title": p.title,
        "description": p.description,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "fieldConfig": {"defaults": {"unit": p.unit}, "overrides": []},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "targets": [
            {
                "expr": t.expr,
                "legendFormat": t.legend,
                "refId": chr(ord("A") + i),
            }
            for i, t in enumerate(p.targets)
        ],
    }


def dashboard_json(title: str = "ray_tpu cluster",
                   panels: Optional[List[Panel]] = None,
                   uid: str = "ray-tpu-default") -> Dict[str, Any]:
    """A complete importable Grafana dashboard document."""
    panels = DEFAULT_PANELS if panels is None else panels
    out_panels = []
    for i, p in enumerate(panels):
        x = (i % 2) * 12
        y = (i // 2) * 8
        out_panels.append(_panel_json(p, i + 1, x, y))
    return {
        "uid": uid,
        "title": title,
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "refresh": "15s",
        "schemaVersion": 39,
        "templating": {"list": [{
            "name": "datasource",
            "type": "datasource",
            "query": "prometheus",
        }]},
        "time": {"from": "now-1h", "to": "now"},
        "panels": out_panels,
    }


def default_dashboard() -> Dict[str, Any]:
    return dashboard_json()


def write_dashboards(out_dir: str) -> List[str]:
    """Write the generated dashboard files (the factory's CLI shape)."""
    import json
    import os

    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "ray_tpu_default_dashboard.json")
    with open(path, "w") as f:
        json.dump(default_dashboard(), f, indent=2)
    return [path]
