"""Whole-run distributed timeline: task events + collected spans →
one Chrome-trace document.

Reference: `ray.timeline()` (`_private/state.py:948`
chrome_tracing_dump) merged with the otel span view the reference
splits across tools.  One builder feeds both surfaces —
`GET /api/timeline` on the dashboard head and `rt.timeline()` — so the
browser view and the programmatic dump can never drift.

Event mapping:

- FINISHED/FAILED task events with a duration → complete (`ph:"X"`)
  slices, one lane per worker, exactly the pre-existing view;
- tasks whose LATEST state in the window is SUBMITTED/RUNNING →
  begin (`ph:"B"`) events, so in-flight work is VISIBLE instead of
  silently dropped (Perfetto renders an unclosed B to the end of the
  trace — which is the truth: it hasn't finished);
- collected spans (driver submit/retry, daemon sched hops, worker
  run spans) → `cat:"span"` slices laned by reporting process, with
  `trace_id`/`span_id`/`parent_id` in `args` so one logical request is
  correlated across every process that touched it;
- the document carries a `truncated` flag whenever either source
  window clipped (ring eviction or query limit) — the old endpoint
  capped at 50k events with no signal.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

_TERMINAL = ("FINISHED", "FAILED")
_LIVE = ("SUBMITTED", "RUNNING")


def _task_trace_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    # latest state per task decides whether it gets a B event; terminal
    # events break timestamp ties (events from different processes land
    # in the ring in arbitrary order)
    latest: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        tid = ev.get("task_id")
        state = ev.get("state")
        if not tid or state is None:
            continue
        if state in _TERMINAL and ev.get("duration"):
            dur_us = ev["duration"] * 1e6
            out.append({
                "name": ev.get("name", "task"),
                "cat": "task",
                "ph": "X",
                "ts": ev["ts"] * 1e6 - dur_us,
                "dur": dur_us,
                "pid": ev.get("node_id", "cluster"),
                "tid": ev.get("worker_id", tid[:8]),
                "args": {"task_id": tid, "state": state},
            })
        cur = latest.get(tid)
        rank = 1 if state in _TERMINAL else 0
        key = (ev.get("ts", 0.0), rank)
        if cur is None or key >= cur["_key"]:
            latest[tid] = {**ev, "_key": key}
    for tid, ev in latest.items():
        if ev.get("state") not in _LIVE:
            continue
        out.append({
            "name": ev.get("name", "task"),
            "cat": "task",
            "ph": "B",
            "ts": ev["ts"] * 1e6,
            "pid": ev.get("node_id", "cluster"),
            "tid": ev.get("worker_id", tid[:8]),
            "args": {"task_id": tid, "state": ev.get("state")},
        })
    return out


def _span_trace_events(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    out = []
    for s in spans:
        start = s.get("start")
        if start is None:
            continue
        end = s.get("end", start)
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
            "kind": s.get("kind"),
        }
        if s.get("error"):
            args["error"] = s["error"]
        if s.get("attrs"):
            args.update(s["attrs"])
        out.append({
            "name": s.get("name", "span"),
            "cat": "span",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(1.0, (end - start) * 1e6),
            "pid": s.get("node", "cluster"),
            "tid": s.get("proc", "?"),
            "args": args,
        })
    return out


def build_chrome_trace(events: List[Dict[str, Any]],
                       spans: Optional[List[Dict[str, Any]]] = None,
                       *,
                       events_truncated: bool = False,
                       spans_truncated: bool = False) -> Dict[str, Any]:
    """The merged timeline document: `{"traceEvents": [...],
    "truncated": bool, ...}` — the Chrome trace 'object format', loads
    directly in chrome://tracing and Perfetto."""
    trace = _task_trace_events(events)
    trace.extend(_span_trace_events(spans or []))
    trace.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": trace,
        "truncated": bool(events_truncated or spans_truncated),
        "events_truncated": bool(events_truncated),
        "spans_truncated": bool(spans_truncated),
    }
