"""Cluster dashboard: HTTP observability endpoint.

Reference: `python/ray/dashboard/` (`DashboardHead`, `dashboard/head.py:61`,
module plugins under `dashboard/modules/`).  One dashboard actor serves
JSON APIs over the controller's state (nodes/actors/tasks/jobs/PGs/
autoscaler/serve), Prometheus metrics, a chrome-trace timeline, and a
small self-contained HTML page — the React client's job, minus the
build system.
"""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
