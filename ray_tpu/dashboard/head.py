"""DashboardHead actor.

Reference: `dashboard/head.py:61` DashboardHead + module routes
(`dashboard/modules/{node,actor,job,serve,metrics}`).  Async actor: the
listen socket and all handlers live on the worker's io loop, state is
fetched from the controller with async calls (never blocking the loop).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, Dict, Optional, Tuple

from ray_tpu.serve.request import Request
from ray_tpu.util import httpd

logger = logging.getLogger(__name__)

_SPA_CACHE: Optional[str] = None


def _load_spa() -> str:
    """The buildless single-file SPA (app.html, served at `/`) —
    capability parity with the reference's React client
    (`dashboard/client/src/App.tsx`: live task/actor/node/job tables
    with filters, inline timeline, metric sparklines, log tail)
    without any npm pipeline.  Read once and cached — handlers run on
    the actor's io loop and must not do per-request disk I/O.  Falls
    back to the minimal inline page if the file is missing."""
    global _SPA_CACHE
    if _SPA_CACHE is None:
        import os

        path = os.path.join(os.path.dirname(__file__), "app.html")
        try:
            # read-once, cached for the process lifetime (the module
            # docstring's no-per-request-disk-IO contract)
            with open(path, encoding="utf-8") as f:  # rtlint: disable=RT009
                _SPA_CACHE = f.read()
        except OSError:
            _SPA_CACHE = _FALLBACK_PAGE
    return _SPA_CACHE


_FALLBACK_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 2em; background: #111; color: #eee; }
 h1 { font-size: 1.2em; } h2 { font-size: 1em; margin-top: 1.5em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #444; padding: 4px 8px; text-align: left; }
 a { color: #8cf; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="status"></div>
<h2>nodes</h2><div id="nodes"></div>
<h2>actors</h2><div id="actors"></div>
<h2>jobs</h2><div id="jobs"></div>
<h2>recent tasks</h2><div id="tasks"></div>
<script>
function table(rows) {
  if (!rows || !rows.length) return "<i>none</i>";
  const cols = Object.keys(rows[0]);
  let h = "<table><tr>" + cols.map(c => "<th>"+c+"</th>").join("") + "</tr>";
  for (const r of rows)
    h += "<tr>" + cols.map(c => "<td>"+JSON.stringify(r[c])+"</td>").join("") + "</tr>";
  return h + "</table>";
}
async function refresh() {
  const s = await (await fetch("api/cluster_status")).json();
  document.getElementById("status").innerHTML = "<pre>"+JSON.stringify(s, null, 1)+"</pre>";
  for (const [id, url] of [["nodes","api/nodes"],["actors","api/actors"],
                           ["jobs","api/jobs"],["tasks","api/tasks?limit=25"]]) {
    document.getElementById(id).innerHTML = table(await (await fetch(url)).json());
  }
}
refresh(); setInterval(refresh, 5000);
</script></body></html>"""


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._server = None

    async def start(self) -> int:
        self._server, self._port = await httpd.serve_http(
            self._host, self._port, self._dispatch
        )
        return self._port

    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    async def stop(self) -> bool:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        return True

    # -- routing ------------------------------------------------------
    async def _ctl(self, method: str, payload: Optional[Dict] = None):
        from ray_tpu.core.runtime import get_runtime

        return await get_runtime().controller.call(method, payload)

    async def _dispatch(self, req: Request) -> Tuple[int, str, bytes]:
        path = req.path.rstrip("/") or "/"
        if path == "/":
            return 200, "text/html; charset=utf-8", _load_spa().encode()
        if path == "/api/cluster_status":
            nodes = await self._ctl("get_nodes")
            actors = await self._ctl("list_actors")
            auto = await self._ctl("get_autoscaler_state")
            # controller-side reduction with a TTL cache: no 50k-event
            # RPC per poll (the SPA hits this every 2 s)
            summary = await self._ctl("task_state_summary") or {}
            return httpd.json_response({
                "nodes_alive": sum(1 for n in nodes if n["alive"]),
                "actors_alive": sum(1 for a in actors if a["state"] == "ALIVE"),
                "pending_demands": auto["pending_demands"],
                "task_summary": summary,
            })
        if path == "/api/nodes":
            return httpd.json_response(await self._ctl("get_nodes"))
        if path == "/api/actors":
            return httpd.json_response(await self._ctl("list_actors"))
        if path == "/api/placement_groups":
            return httpd.json_response(await self._ctl("list_placement_groups"))
        if path == "/api/jobs" and req.method == "POST":
            # REST job submission (reference: `dashboard/modules/job/
            # job_head.py:329` POST /api/jobs/): body {entrypoint,
            # submission_id?, env?, working_dir?, metadata?}
            try:
                body = req.json()
                entrypoint = body["entrypoint"]
            except Exception as e:
                logger.debug("malformed job submission body: %s", e)
                return httpd.json_response(
                    {"error": "body must be JSON with 'entrypoint'"},
                    status=400,
                )
            loop = asyncio.get_running_loop()

            def _submit():
                from ray_tpu.job import api as job_api

                return job_api.submit_job(
                    entrypoint,
                    submission_id=body.get("submission_id"),
                    env=body.get("env"),
                    working_dir=body.get("working_dir"),
                    metadata=body.get("metadata"),
                )

            try:
                job_id = await loop.run_in_executor(None, _submit)
            except ValueError as e:  # duplicate submission_id etc.
                return httpd.json_response({"error": str(e)}, status=400)
            return httpd.json_response(
                {"job_id": job_id, "submission_id": job_id}
            )
        if path.startswith("/api/jobs/"):
            parts = path.split("/")  # ['', 'api', 'jobs', <id>, (verb)]
            job_id = parts[3]
            verb = parts[4] if len(parts) > 4 else None
            loop = asyncio.get_running_loop()
            from ray_tpu.job import api as job_api

            try:
                if verb is None and req.method == "GET":
                    info = await loop.run_in_executor(
                        None, job_api.get_job_info, job_id
                    )
                    return httpd.json_response(info)
                if verb == "logs" and req.method == "GET":
                    logs = await loop.run_in_executor(
                        None, job_api.get_job_logs, job_id
                    )
                    return 200, "text/plain; charset=utf-8", logs.encode()
                if verb == "stop" and req.method == "POST":
                    stopped = await loop.run_in_executor(
                        None, job_api.stop_job, job_id
                    )
                    return httpd.json_response({"stopped": bool(stopped)})
            except ValueError as e:  # unknown job id
                return httpd.json_response({"error": str(e)}, status=404)
            return httpd.json_response({"error": "unsupported"}, status=405)
        if path == "/api/jobs":
            jobs = await self._ctl("list_jobs") or []
            # submitted (supervised) jobs live in the KV
            keys = await self._ctl("kv_keys", {"prefix": "job:"}) or []
            from ray_tpu.core.runtime import get_runtime

            rt_ = get_runtime()
            for key in keys:
                raw = await rt_.controller.call("kv_get", {"key": key})
                if raw:
                    jobs.append(json.loads(raw))
            return httpd.json_response(jobs)
        if path == "/api/workers":
            snap = await self._ctl("get_worker_snapshot")
            return httpd.json_response(snap or [])
        if path == "/api/memory":
            # object-ref memory debugging (reference: `ray memory` —
            # `_private/internal_api.py:34`): per-node reference tables
            # + store occupancy, aggregated over live nodes
            from ray_tpu.core.runtime import get_runtime

            rt_ = get_runtime()
            tables = []
            for n in (await self._ctl("get_nodes")) or []:
                if not n.get("alive"):
                    continue
                try:
                    t = await rt_.noded.call("route_node", {
                        "node_id": n["node_id"],
                        "method": "memory_table",
                    }, timeout=20)
                except Exception as e:
                    # node died between listing and the call
                    logger.debug("memory_table from %s failed: %s",
                                 n["node_id"][:8], e)
                    continue
                if t:
                    tables.append(t)
            return httpd.json_response(tables)
        if path == "/api/profile":
            # on-demand worker stack profile (reference: py-spy via
            # `modules/reporter/profile_manager.py:78`)
            node_id = req.query_params.get("node_id")
            worker_id = req.query_params.get("worker_id")
            if not node_id or not worker_id:
                return httpd.json_response(
                    {"error": "node_id and worker_id query params required"},
                    status=400,
                )
            from ray_tpu.core.runtime import get_runtime

            mode = req.query_params.get("mode", "stacks")
            duration = min(
                float(req.query_params.get("duration", "5")), 60.0
            )
            reply = await get_runtime().noded.call(
                "route_node",
                {"node_id": node_id, "method": "profile_worker",
                 "payload": {
                     "worker_id": worker_id,
                     "native": req.query_params.get("native") == "1",
                     "mode": mode,
                     "duration_s": duration,
                 }},
                timeout=duration + 40,
            )
            if mode == "flamegraph" and isinstance(reply, dict) \
                    and "stacks" in reply:
                # folded stacks as plain text: paste straight into
                # speedscope / flamegraph.pl
                return (200, "text/plain; charset=utf-8",
                        str(reply["stacks"]).encode())
            return httpd.json_response(reply)
        if path == "/api/tasks":
            limit = int(req.query_params.get("limit", "100"))
            events = await self._ctl("list_task_events", {"limit": limit})
            return httpd.json_response(events)
        if path == "/api/cluster_events":
            # structured event log (reference: `dashboard/modules/event/`)
            events = await self._ctl("list_cluster_events", {
                "limit": int(req.query_params.get("limit", "200")),
                "severity": req.query_params.get("severity"),
                "event_type": req.query_params.get("event_type"),
            })
            return httpd.json_response(events or [])
        if path == "/api/grafana_dashboard":
            from ray_tpu.dashboard.grafana import default_dashboard

            return httpd.json_response(default_dashboard())
        if path == "/api/timeline":
            # whole-run merged timeline (dashboard/timeline.py): task
            # events + collected spans in one Chrome-trace document,
            # with honest truncation flags.  ?trace_id= narrows the
            # span set to one logical request's lineage.
            from ray_tpu.dashboard.timeline import build_chrome_trace

            limit = int(req.query_params.get("limit", "50000"))
            data = await self._ctl("timeline_data", {
                "trace_id": req.query_params.get("trace_id"),
                "limit_events": limit,
                "limit_spans": limit,
            }) or {}
            return httpd.json_response(build_chrome_trace(
                data.get("events", []),
                data.get("spans", []),
                events_truncated=data.get("events_truncated", False),
                spans_truncated=data.get("spans_truncated", False),
            ))
        if path == "/api/serve":
            try:
                from ray_tpu.serve.api import _get_controller_async
                from ray_tpu.core.runtime import get_runtime

                controller = await _get_controller_async()
                ref = controller.get_serve_status.remote()
                status = await get_runtime()._get_one(ref)
                return httpd.json_response(status)
            except Exception as e:
                # no serve controller deployed yet: an empty status is
                # the correct answer, not an error page
                logger.debug("serve status unavailable: %s", e)
                return httpd.json_response({})
        if path == "/api/slo":
            # per-deployment SLO burn rates (serve/slo.py): configured
            # targets + multi-window burn rates + ok verdict, folded
            # by the controller from the replicas' ledger counters
            try:
                from ray_tpu.serve.api import _get_controller_async
                from ray_tpu.core.runtime import get_runtime

                controller = await _get_controller_async()
                ref = controller.get_slo_status.remote()
                status = await get_runtime()._get_one(ref)
                return httpd.json_response(status)
            except Exception as e:
                logger.debug("slo status unavailable: %s", e)
                return httpd.json_response({})
        if path == "/api/serve/applications":
            # REST deploy (reference: `dashboard/modules/serve/` REST API
            # + `serve/schema.py` app config): PUT deploys an app whose
            # bound graph is named by import_path "module:variable"
            if req.method == "PUT":
                body = req.json()
                loop = asyncio.get_running_loop()

                def _deploy():
                    from ray_tpu.serve import schema as serve_schema

                    # reference-shaped multi-app document
                    # (`serve/schema.py` ServeDeploySchema) or the
                    # single-app shorthand {import_path, name, ...}
                    doc = (
                        body if "applications" in body
                        else {"applications": [body]}
                    )
                    return serve_schema.deploy_from_schema(doc)

                try:
                    deployed = await loop.run_in_executor(None, _deploy)
                except Exception as e:  # validation errors -> 400
                    return httpd.json_response(
                        {"error": str(e)}, status=400
                    )
                return httpd.json_response(
                    {"ok": True, "applications": deployed}
                )
            return httpd.json_response(
                {"error": "use PUT with a ServeDeploySchema document "
                          "{applications: [{import_path, name, ...}]}"},
                status=405,
            )
        if path.startswith("/api/serve/applications/") and req.method == "DELETE":
            name = path.rsplit("/", 1)[1]
            loop = asyncio.get_running_loop()

            def _delete():
                from ray_tpu import serve

                serve.delete(name)

            await loop.run_in_executor(None, _delete)
            return httpd.json_response({"ok": True})
        if path == "/api/logs":
            # session log browser (reference: `dashboard/modules/log/`);
            # filesystem walks/reads run off the loop like every other
            # blocking handler here
            loop = asyncio.get_running_loop()
            file = req.query_params.get("file")
            if file:
                def _tail():
                    import os

                    base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
                    # constrain to the session tree — no path escapes
                    full = os.path.realpath(os.path.join(base, file))
                    if not full.startswith(os.path.realpath(base) + os.sep):
                        return None
                    try:
                        with open(full, "rb") as f:
                            f.seek(0, os.SEEK_END)
                            size = f.tell()
                            f.seek(max(0, size - 64 * 1024))
                            return f.read()
                    except OSError:
                        return None

                data = await loop.run_in_executor(None, _tail)
                if data is None:
                    return 404, "text/plain", b"not found"
                return 200, "text/plain; charset=utf-8", data

            def _list():
                import glob
                import os

                base = os.environ.get("RT_TMPDIR", "/tmp/ray_tpu")
                return sorted(
                    os.path.relpath(p, base)
                    for p in glob.glob(base + "/**/*", recursive=True)
                    if os.path.isfile(p)
                    and (p.endswith(".out") or p.endswith(".log"))
                )

            return httpd.json_response(
                await loop.run_in_executor(None, _list)
            )
        if path == "/metrics":
            from ray_tpu.metrics.registry import render_exposition, snapshot

            # refresh the built-in cluster gauges at scrape time so the
            # Prometheus view (and the generated Grafana dashboard)
            # reflects controller state without a push pipeline
            try:
                from ray_tpu.dashboard.grafana import update_builtin_metrics

                await update_builtin_metrics(self._ctl)
            except Exception as e:
                logger.debug("builtin gauge refresh failed: %s", e)
            # one scrape serves the whole cluster: this process's
            # registry (builtin gauges, serve bridge) merged with the
            # controller sink's collected per-process snapshots, every
            # sample origin-tagged node/proc so series stay distinct.
            # The sink also holds THIS process's reporter (the obs
            # frame loop ships it) — filter that copy out, or every
            # local series would export twice and double any
            # sum()/rate() aggregation over it
            import os as _os

            from ray_tpu.core.runtime import get_runtime

            rt_ = get_runtime()
            me = {"node": (rt_.node_id or "")[:8],
                  "proc": f"{rt_.mode}:{_os.getpid()}"}
            merged = snapshot(extra_tags=me)
            try:
                cluster = await self._ctl("cluster_metrics", {}) or {}
                for m in cluster.get("metrics", []):
                    samples = [
                        s for s in m.get("samples", ())
                        if not ((s[0] or {}).get("proc") == me["proc"]
                                and (s[0] or {}).get("node") == me["node"])
                    ]
                    if samples:
                        merged.append({**m, "samples": samples})
            except Exception as e:
                # local exposition still serves (degraded, not down)
                logger.debug("cluster metrics fetch failed: %s", e)
            return (200, "text/plain; version=0.0.4",
                    render_exposition(merged).encode())
        return 404, "text/plain", b"not found"


def start_dashboard(host: str = "127.0.0.1", port: int = 0):
    """Launch the dashboard actor; returns (handle, (host, port))."""
    import ray_tpu as rt

    head = (
        rt.remote(DashboardHead)
        .options(name="DASHBOARD_HEAD", max_concurrency=8, num_cpus=0)
        .remote(host, port)
    )
    bound = rt.get(head.start.remote())
    return head, (host, bound)
