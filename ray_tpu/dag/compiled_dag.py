"""DAG compilation and driver-side execution.

Reference: `python/ray/dag/compiled_dag_node.py` (CompiledDAG) and
`python/ray/experimental/compiled_dag_ref.py` (CompiledDAGRef).

Compilation walks the bound graph, groups nodes by actor, allocates a
channel per cross-actor edge, and launches one resident exec loop per
actor (execution.py).  execute() writes the input channels and returns a
CompiledDAGRef that reads the output channels — per-execution cost is
channel ops only.  Ring-buffered channels bound pipelined in-flight
executions the way the reference's buffered channels do.

Failure model: channels cannot observe a SIGKILLed peer, so the driver
watches the resident loop TASKS — when one fails (actor death, channel
wedge), the dead actor's outgoing channels are poisoned with the typed
error, which the surviving downstream loops propagate stage-to-stage
until it reaches every consumer and the driver's CompiledDAGRef.
"""

from __future__ import annotations

import itertools
import logging
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu import exceptions as exc
from ray_tpu.dag import execution as ex
from ray_tpu.dag.channel import Channel, ChannelClosed, ChannelPollTimeout
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

logger = logging.getLogger(__name__)

_exec_counter = itertools.count()

# slice width for blocking output reads: long waits are chopped so the
# driver notices a dead stage (loop-task failure) instead of blocking
# the full timeout against a ring nobody will ever write
_POLL_SLICE_S = 0.25


class _Expired(Exception):
    """Internal: the collect deadline passed with NOTHING consumed —
    distinct from a user TimeoutError payload read off the channel."""


def reap_failed_loop_tasks(loop_refs, reaped: set):
    """Poll resident-loop task refs (non-blocking) and return
    [(ref, error)] for loops that finished WITH a failure — the shared
    dead-stage detector behind CompiledDAG, the 1F1B pipeline, and the
    rllib channel plane (channels cannot observe a SIGKILLed peer; the
    loop TASK failing is the signal).  Clean exits (teardown: the loop
    returns its execution count) are just marked reaped."""
    import ray_tpu as rt

    candidates = [r for r in loop_refs if r not in reaped]
    if not candidates:
        return []
    try:
        done, _ = rt.wait(candidates, num_returns=len(candidates),
                          timeout=0)
    except Exception as e:
        logger.debug("loop-ref poll failed: %s", e)
        return []
    out = []
    for ref in done:
        reaped.add(ref)
        try:
            rt.get(ref, timeout=5)
        except BaseException as e:  # rtlint: disable=RT005 — not
            # swallowed: returned for the caller to surface (poison /
            # raise / replace)
            out.append((ref, e))
    return out


def resolve_actor_node(handle) -> str:
    """The node currently hosting an actor.  Always refreshed via the
    controller: a handle caches its creation-time address, and an actor
    restarted on another node would otherwise get channel rings placed
    on the old node.  Shared by CompiledDAG, the 1F1B pipeline, and the
    rllib channel plane."""
    from ray_tpu.core.runtime import get_runtime

    aid = handle._actor_id.binary()
    addr = None
    try:
        info = get_runtime().controller_call("get_actor", {"actor_id": aid})
        if info and info.get("address"):
            addr = tuple(info["address"])
    except Exception as e:
        logger.debug("actor %s address refresh failed (%s); using the "
                     "handle's cached address", aid.hex()[:12], e)
    if addr is None:
        addr = handle._address
    if addr is None:
        raise RuntimeError(
            f"actor {aid.hex()[:12]} has no known address (still "
            "scheduling?)"
        )
    return addr[0]


class CompiledDAGRef:
    """Future for one execute() call (reference:
    `experimental/compiled_dag_ref.py`); get() may be called once per
    execution, in order.

    get() honors the ambient end-to-end deadline (PR 1 plumbing): when
    the executing task's `remaining_deadline_s()` is narrower than the
    requested timeout, the wait is clamped to it and expiry raises the
    typed `DeadlineExceededError` the rest of the stack speaks."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def get(self, timeout: Optional[float] = 30.0):
        from ray_tpu.core.runtime import remaining_deadline_s

        deadline_bound = False
        rem = remaining_deadline_s()
        if rem is not None and (timeout is None or rem < timeout):
            timeout, deadline_bound = rem, True
        if not self._done:
            self._dag._collect_until(self._idx, timeout, deadline_bound)
        if self._error is not None:
            raise self._error
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, max_inflight: int = 4):
        self._id = uuid.uuid4().hex[:8]
        self._max_inflight = max_inflight
        self._torn_down = False
        self._next_exec = 0
        self._next_collect = 0
        self._pending: Dict[int, CompiledDAGRef] = {}
        self._partial: List[Any] = []  # outputs read so far for the
        # execution currently being collected (resume after timeout)
        self._loops_reaped: set = set()  # loop refs already diagnosed
        self._poisoned: set = set()  # actor ids whose failure was injected

        if isinstance(root, MultiOutputNode):
            self._outputs: List[DAGNode] = root.outputs
            self._multi = True
        else:
            self._outputs = [root]
            self._multi = False
        for o in self._outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError("DAG leaves must be actor method nodes")

        self._compile()

    # -- compilation ---------------------------------------------------
    def _chan_name(self, producer: int, consumer: str) -> str:
        return f"dag{self._id}_e{producer}_{consumer}"

    def _compile(self):
        # topological order over the method nodes
        order: List[ClassMethodNode] = []
        seen = set()

        def visit(n: DAGNode):
            if n._id in seen:
                return
            seen.add(n._id)
            for u in n._upstream():
                visit(u)
            if isinstance(n, ClassMethodNode):
                order.append(n)

        for o in self._outputs:
            visit(o)
        self._order = order

        by_actor: Dict[bytes, List[ClassMethodNode]] = {}
        actor_handles: Dict[bytes, Any] = {}
        node_actor: Dict[int, bytes] = {}
        for n in order:
            aid = n.actor._actor_id.binary()
            by_actor.setdefault(aid, []).append(n)
            actor_handles[aid] = n.actor
            node_actor[n._id] = aid

        # each channel's ring lives on its READER's node; writers on
        # other nodes relay through the daemons (channel.py) — so the
        # graph may span nodes freely (reference: cross-node mutable
        # objects, `experimental_mutable_object_provider.h`)
        from ray_tpu.core.runtime import get_runtime

        driver_node = get_runtime().node_id
        actor_node: Dict[bytes, str] = {
            aid: resolve_actor_node(h) for aid, h in actor_handles.items()
        }

        # consumers per produced node, to know which edges cross actors
        plans: Dict[bytes, Dict] = {
            aid: {"input_channel": None, "steps": []} for aid in by_actor
        }
        self._input_channels: List[Channel] = []
        self._mid_channels: List[Tuple[str, str]] = []

        def arg_source(consumer: ClassMethodNode, arg) -> Tuple[str, Any]:
            if isinstance(arg, InputNode):
                aid = node_actor[consumer._id]
                if plans[aid]["input_channel"] is None:
                    # full actor id: ids embed a shared job prefix, so a
                    # short prefix collides across actors
                    name = f"dag{self._id}_in_{aid.hex()}"
                    loc = actor_node[aid]  # ring on the reading actor
                    plans[aid]["input_channel"] = (name, loc)
                    self._input_channels.append(Channel(name, loc))
                return (ex.SRC_INPUT, None)
            if isinstance(arg, ClassMethodNode):
                if node_actor[arg._id] == node_actor[consumer._id]:
                    return (ex.SRC_LOCAL, arg._id)
                name = self._chan_name(arg._id, f"n{consumer._id}")
                loc = actor_node[node_actor[consumer._id]]  # reader side
                # register the edge on the producer's step
                producer_step[arg._id]["out_channels"].append((name, loc))
                self._mid_channels.append((name, loc))
                return (ex.SRC_CHAN, (name, loc))
            if isinstance(arg, DAGNode):
                raise TypeError(f"unsupported node type {type(arg)}")
            return (ex.SRC_CONST, arg)

        producer_step: Dict[int, Dict] = {}
        for n in order:
            step = {
                "node_id": n._id,
                "method": n.method_name,
                "args": [],
                "kwargs": {},
                "out_channels": [],
            }
            producer_step[n._id] = step
            plans[node_actor[n._id]]["steps"].append(step)
        for n in order:
            step = producer_step[n._id]
            step["args"] = [arg_source(n, a) for a in n.args]
            step["kwargs"] = {k: arg_source(n, v) for k, v in n.kwargs.items()}

        # output channels: leaves -> driver (rings on the driver's node)
        self._output_channels: List[Channel] = []
        for i, o in enumerate(self._outputs):
            name = self._chan_name(o._id, f"out{i}")
            producer_step[o._id]["out_channels"].append((name, driver_node))
            self._output_channels.append(Channel(name, driver_node))

        # launch one resident loop per actor (framework-reserved method;
        # the runtime routes it to execution.dag_exec_loop)
        for aid, plan in plans.items():
            if plan["input_channel"] is None and not any(
                src == ex.SRC_CHAN
                for step in plan["steps"]
                for src, _ in [*step["args"], *step["kwargs"].values()]
            ):
                raise ValueError(
                    "every actor in a compiled DAG must be driven by the "
                    "InputNode or an upstream channel (unbounded source "
                    "loops are not allowed)"
                )

        from ray_tpu.api import ActorMethod

        self._loop_refs = []
        self._loop_owner: Dict[Any, bytes] = {}  # loop ref -> actor id
        self._plans = plans
        self._actors = list(actor_handles.values())
        for aid, plan in plans.items():
            h = actor_handles[aid]
            ref = ActorMethod(h, "__rt_dag_exec_loop__").remote(plan)
            self._loop_refs.append(ref)
            self._loop_owner[ref] = aid

    # -- execution -----------------------------------------------------
    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if len(self._pending) >= self._max_inflight:
            self._collect_until(self._next_collect, timeout=120.0)
        if self._input_channels:
            if len(args) != 1:
                raise TypeError(
                    "execute() takes exactly one input (the InputNode value)"
                )
            for ch in self._input_channels:
                ch.write(args[0])
        elif args:
            raise TypeError("this DAG has no InputNode; execute() takes no args")
        idx = self._next_exec
        self._next_exec += 1
        ref = CompiledDAGRef(self, idx)
        self._pending[idx] = ref
        return ref

    # -- failure detection --------------------------------------------
    def _check_loops(self):
        """Reap failed resident-loop tasks and inject their error into
        the dead actor's outgoing channels.  Only called from the slow
        path (an output read slice timed out): a healthy DAG never pays
        for this."""
        for ref, e in reap_failed_loop_tasks(self._loop_refs,
                                             self._loops_reaped):
            self._poison_actor(self._loop_owner.get(ref), e)

    def _poison_actor(self, aid: Optional[bytes], cause: BaseException):
        """Write the typed failure into every channel the dead actor
        feeds, so each downstream stage (and the driver) unblocks with
        the error instead of hanging on a ring nobody will write."""
        if aid is None or aid in self._poisoned:
            return
        self._poisoned.add(aid)
        err = exc.ActorDiedError(
            f"compiled-DAG stage actor {aid.hex()[:12]} died "
            f"mid-execution: {cause!r}"
        )
        for step in self._plans[aid]["steps"]:
            for name, loc in step["out_channels"]:
                ch = Channel(name, loc)
                try:
                    ch.write_error(err)
                except Exception as e:
                    # full ring or torn-down region: the close below
                    # still unblocks the reader (as ChannelClosed)
                    logger.debug("poison write to %s failed (%s); "
                                 "relying on close", name, e)
                # then the teardown sentinel: downstream loops consume
                # the error, forward it, and exit instead of re-parking
                # on a ring the dead stage will never write again
                ch.close()

    def _collect_until(self, idx: int, timeout: Optional[float],
                       deadline_bound: bool = False):
        """Reads results in execution order up to and including idx.

        A read timeout leaves collection state untouched (the channel
        read_seq only advances on success, and `_partial` resumes where
        it left off), so a slow execution can be re-polled without
        shifting later results by one.  Blocking reads are sliced so a
        SIGKILLed stage is detected (its loop task fails) and its typed
        error injected, instead of blocking the full timeout.
        """
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while self._next_collect <= idx:
            ref = self._pending.get(self._next_collect)
            error = None
            while len(self._partial) < len(self._output_channels):
                ch = self._output_channels[len(self._partial)]
                try:
                    self._partial.append(self._read_sliced(ch, deadline))
                except ChannelClosed:
                    self._partial.append(None)
                    error = RuntimeError("DAG torn down mid-execution")
                except _Expired:
                    # caller may retry; nothing was consumed (a USER
                    # TimeoutError payload is consumed before raising
                    # and takes the branch below instead)
                    self._raise_expired(deadline_bound)
                except BaseException as e:  # rtlint: disable=RT005 — not
                    # swallowed: stored and re-raised by ref.get()
                    self._partial.append(None)
                    error = e
            values, self._partial = self._partial, []
            self._pending.pop(self._next_collect, None)
            self._next_collect += 1
            if ref is not None:
                ref._done = True
                ref._error = error
                ref._value = (
                    values if self._multi else (values[0] if values else None)
                )

    def _read_sliced(self, ch: Channel, deadline: Optional[float]):
        while True:
            if deadline is None:
                step = _POLL_SLICE_S
            else:
                # even a spent deadline gets one minimal poll: get(0)
                # must return an ALREADY-published result, not time out
                step = min(_POLL_SLICE_S,
                           max(0.001, deadline - time.monotonic()))
            try:
                return ch.read(timeout_s=step)
            except ChannelPollTimeout:
                # slow path only: notice dead stages, then keep waiting
                self._check_loops()
                if (deadline is not None
                        and time.monotonic() >= deadline):
                    raise _Expired() from None

    def _raise_expired(self, deadline_bound: bool):
        if deadline_bound:
            raise exc.DeadlineExceededError(
                "ambient deadline expired while waiting for DAG output"
            ) from None
        raise TimeoutError("timed out waiting for DAG output") from None

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu as rt

        for ch in self._input_channels:
            ch.close()
        # loops forward the sentinel; wait for them to exit
        try:
            _, still_running = rt.wait(
                self._loop_refs, num_returns=len(self._loop_refs),
                timeout=10,
            )
        except Exception as e:
            logger.debug("teardown loop wait failed: %s", e)
            still_running = list(self._loop_refs)
        if still_running:
            # a loop that never saw the sentinel (its upstream died, or
            # it is blocked writing into a dead reader's full ring):
            # close every edge so blocked reads AND writes unwedge
            for name, loc in getattr(self, "_mid_channels", ()):
                Channel(name, loc).close()
            for ch in self._output_channels:
                ch.close()
            try:
                rt.wait(still_running, num_returns=len(still_running),
                        timeout=5)
            except Exception as e:
                logger.debug("teardown second loop wait failed: %s", e)
        # free every channel region: they are pinned + non-evictable,
        # so skipping this would leak arena on every compile/teardown
        for ch in [*self._input_channels, *self._output_channels]:
            ch.destroy()
        for name, loc in getattr(self, "_mid_channels", ()):  # actor-to-
            Channel(name, loc).destroy()  # actor edges (exec-loop opened)

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # rtlint: disable=RT005 — interpreter-teardown
            pass  # destructor; logging machinery may already be gone
