"""DAG compilation and driver-side execution.

Reference: `python/ray/dag/compiled_dag_node.py` (CompiledDAG) and
`python/ray/experimental/compiled_dag_ref.py` (CompiledDAGRef).

Compilation walks the bound graph, groups nodes by actor, allocates a
channel per cross-actor edge, and launches one resident exec loop per
actor (execution.py).  execute() writes the input channels and returns a
CompiledDAGRef that reads the output channels — per-execution cost is
channel ops only.  Ring-buffered channels bound pipelined in-flight
executions the way the reference's buffered channels do.
"""

from __future__ import annotations

import itertools
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag import execution as ex
from ray_tpu.dag.channel import Channel, ChannelClosed, ChannelPollTimeout
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

_exec_counter = itertools.count()


class CompiledDAGRef:
    """Future for one execute() call (reference:
    `experimental/compiled_dag_ref.py`); get() may be called once per
    execution, in order."""

    def __init__(self, dag: "CompiledDAG", idx: int):
        self._dag = dag
        self._idx = idx
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None

    def get(self, timeout: Optional[float] = 30.0):
        if not self._done:
            self._dag._collect_until(self._idx, timeout)
        if self._error is not None:
            raise self._error
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, max_inflight: int = 4):
        self._id = uuid.uuid4().hex[:8]
        self._max_inflight = max_inflight
        self._torn_down = False
        self._next_exec = 0
        self._next_collect = 0
        self._pending: Dict[int, CompiledDAGRef] = {}
        self._partial: List[Any] = []  # outputs read so far for the
        # execution currently being collected (resume after timeout)

        if isinstance(root, MultiOutputNode):
            self._outputs: List[DAGNode] = root.outputs
            self._multi = True
        else:
            self._outputs = [root]
            self._multi = False
        for o in self._outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError("DAG leaves must be actor method nodes")

        self._compile()

    # -- compilation ---------------------------------------------------
    def _chan_name(self, producer: int, consumer: str) -> str:
        return f"dag{self._id}_e{producer}_{consumer}"

    def _compile(self):
        import ray_tpu as rt

        # topological order over the method nodes
        order: List[ClassMethodNode] = []
        seen = set()

        def visit(n: DAGNode):
            if n._id in seen:
                return
            seen.add(n._id)
            for u in n._upstream():
                visit(u)
            if isinstance(n, ClassMethodNode):
                order.append(n)

        for o in self._outputs:
            visit(o)
        self._order = order

        by_actor: Dict[bytes, List[ClassMethodNode]] = {}
        actor_handles: Dict[bytes, Any] = {}
        node_actor: Dict[int, bytes] = {}
        for n in order:
            aid = n.actor._actor_id.binary()
            by_actor.setdefault(aid, []).append(n)
            actor_handles[aid] = n.actor
            node_actor[n._id] = aid

        # each channel's ring lives on its READER's node; writers on
        # other nodes relay through the daemons (channel.py) — so the
        # graph may span nodes freely (reference: cross-node mutable
        # objects, `experimental_mutable_object_provider.h`)
        from ray_tpu.core.runtime import get_runtime

        driver_node = get_runtime().node_id
        actor_node: Dict[bytes, str] = {}
        for aid, h in actor_handles.items():
            # always refresh via the controller: a handle caches its
            # creation-time address, and an actor restarted on another
            # node would otherwise get its rings placed on the old node
            addr = None
            try:
                info = get_runtime().controller_call(
                    "get_actor", {"actor_id": aid}
                )
                if info and info.get("address"):
                    addr = tuple(info["address"])
            except Exception:
                pass
            if addr is None:
                addr = h._address
            if addr is None:
                raise RuntimeError(
                    f"cannot compile DAG: actor {aid.hex()[:12]} has no "
                    "known address (still scheduling?)"
                )
            actor_node[aid] = addr[0]

        # consumers per produced node, to know which edges cross actors
        plans: Dict[bytes, Dict] = {
            aid: {"input_channel": None, "steps": []} for aid in by_actor
        }
        self._input_channels: List[Channel] = []
        self._mid_channels: List[Tuple[str, str]] = []

        def arg_source(consumer: ClassMethodNode, arg) -> Tuple[str, Any]:
            if isinstance(arg, InputNode):
                aid = node_actor[consumer._id]
                if plans[aid]["input_channel"] is None:
                    # full actor id: ids embed a shared job prefix, so a
                    # short prefix collides across actors
                    name = f"dag{self._id}_in_{aid.hex()}"
                    loc = actor_node[aid]  # ring on the reading actor
                    plans[aid]["input_channel"] = (name, loc)
                    self._input_channels.append(Channel(name, loc))
                return (ex.SRC_INPUT, None)
            if isinstance(arg, ClassMethodNode):
                if node_actor[arg._id] == node_actor[consumer._id]:
                    return (ex.SRC_LOCAL, arg._id)
                name = self._chan_name(arg._id, f"n{consumer._id}")
                loc = actor_node[node_actor[consumer._id]]  # reader side
                # register the edge on the producer's step
                producer_step[arg._id]["out_channels"].append((name, loc))
                self._mid_channels.append((name, loc))
                return (ex.SRC_CHAN, (name, loc))
            if isinstance(arg, DAGNode):
                raise TypeError(f"unsupported node type {type(arg)}")
            return (ex.SRC_CONST, arg)

        producer_step: Dict[int, Dict] = {}
        for n in order:
            step = {
                "node_id": n._id,
                "method": n.method_name,
                "args": [],
                "kwargs": {},
                "out_channels": [],
            }
            producer_step[n._id] = step
            plans[node_actor[n._id]]["steps"].append(step)
        for n in order:
            step = producer_step[n._id]
            step["args"] = [arg_source(n, a) for a in n.args]
            step["kwargs"] = {k: arg_source(n, v) for k, v in n.kwargs.items()}

        # output channels: leaves -> driver (rings on the driver's node)
        self._output_channels: List[Channel] = []
        for i, o in enumerate(self._outputs):
            name = self._chan_name(o._id, f"out{i}")
            producer_step[o._id]["out_channels"].append((name, driver_node))
            self._output_channels.append(Channel(name, driver_node))

        # launch one resident loop per actor (framework-reserved method;
        # the runtime routes it to execution.dag_exec_loop)
        for aid, plan in plans.items():
            if plan["input_channel"] is None and not any(
                src == ex.SRC_CHAN
                for step in plan["steps"]
                for src, _ in [*step["args"], *step["kwargs"].values()]
            ):
                raise ValueError(
                    "every actor in a compiled DAG must be driven by the "
                    "InputNode or an upstream channel (unbounded source "
                    "loops are not allowed)"
                )

        from ray_tpu.api import ActorMethod

        self._loop_refs = []
        self._actors = list(actor_handles.values())
        for aid, plan in plans.items():
            h = actor_handles[aid]
            self._loop_refs.append(
                ActorMethod(h, "__rt_dag_exec_loop__").remote(plan)
            )

    # -- execution -----------------------------------------------------
    def execute(self, *args) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG was torn down")
        if len(self._pending) >= self._max_inflight:
            self._collect_until(self._next_collect, timeout=120.0)
        if self._input_channels:
            if len(args) != 1:
                raise TypeError(
                    "execute() takes exactly one input (the InputNode value)"
                )
            for ch in self._input_channels:
                ch.write(args[0])
        elif args:
            raise TypeError("this DAG has no InputNode; execute() takes no args")
        idx = self._next_exec
        self._next_exec += 1
        ref = CompiledDAGRef(self, idx)
        self._pending[idx] = ref
        return ref

    def _collect_until(self, idx: int, timeout: Optional[float]):
        """Reads results in execution order up to and including idx.

        A read timeout leaves collection state untouched (the channel
        read_seq only advances on success, and `_partial` resumes where
        it left off), so a slow execution can be re-polled without
        shifting later results by one.
        """
        while self._next_collect <= idx:
            ref = self._pending.get(self._next_collect)
            error = None
            while len(self._partial) < len(self._output_channels):
                ch = self._output_channels[len(self._partial)]
                try:
                    self._partial.append(ch.read(timeout_s=timeout))
                except ChannelClosed:
                    self._partial.append(None)
                    error = RuntimeError("DAG torn down mid-execution")
                except ChannelPollTimeout:
                    # caller may retry; nothing was consumed (a USER
                    # TimeoutError payload is consumed before raising
                    # and takes the branch below instead)
                    raise TimeoutError(
                        "timed out waiting for DAG output"
                    ) from None
                except BaseException as e:  # noqa: BLE001 — stored below
                    self._partial.append(None)
                    error = e
            values, self._partial = self._partial, []
            self._pending.pop(self._next_collect, None)
            self._next_collect += 1
            if ref is not None:
                ref._done = True
                ref._error = error
                ref._value = (
                    values if self._multi else (values[0] if values else None)
                )

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu as rt

        for ch in self._input_channels:
            ch.close()
        # loops forward the sentinel; wait for them to exit
        try:
            rt.wait(self._loop_refs, num_returns=len(self._loop_refs),
                    timeout=10)
        except Exception:
            pass
        # free every channel region: they are pinned + non-evictable,
        # so skipping this would leak arena on every compile/teardown
        for ch in [*self._input_channels, *self._output_channels]:
            ch.destroy()
        for name, loc in getattr(self, "_mid_channels", ()):  # actor-to-
            Channel(name, loc).destroy()  # actor edges (exec-loop opened)

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
