"""Resident exec loops for compiled DAGs (runs inside actor workers).

Reference: `python/ray/dag/compiled_dag_node.py` (`do_exec_tasks:92`,
`ExecutableTask:281`) — after compilation each participating actor runs
one long-lived loop: read input channels, run the bound methods in local
topological order, write output channels.  The per-call submit/lease/
ownership machinery is bypassed entirely; only channel ops remain on the
hot path.  Array-valued step outputs (and tuples/lists/dicts of arrays
— a multi-output step) ride the tensor fast path: one slot publication
per consumer, no pickle on the array bytes (channel.py KIND_TENSOR).
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from ray_tpu.dag.channel import (
    KIND_DATA,
    Channel,
    ChannelClosed,
)
from ray_tpu.metrics import metric_defs as _mdefs

logger = logging.getLogger(__name__)

# arg-source tags in the compiled plan
SRC_CONST = "const"
SRC_LOCAL = "local"  # upstream node output on the same actor
SRC_CHAN = "chan"  # read from a channel (cross-actor edge)
SRC_INPUT = "input"  # the per-execution driver input


def dag_exec_loop(instance: Any, plan: Dict) -> int:
    """plan = {
        "input_channel": (name, location) | None,
        "steps": [
            {"node_id", "method", "args": [(src, payload), ...],
             "kwargs": {k: (src, payload)},
             "out_channels": [(name, location)]},  # cross-actor edges
        ],
    }
    Channel refs are (name, ring-location-node); rings live on their
    reader's node, so reads here are always local and writes relay
    through the daemons when the consumer is on another node.
    Returns the number of completed executions (after teardown)."""
    input_chan = (
        Channel(*plan["input_channel"]) if plan.get("input_channel") else None
    )
    chans: Dict[str, Channel] = {}

    def chan(ref) -> Channel:
        name, loc = ref
        c = chans.get(name)
        if c is None:
            c = chans[name] = Channel(name, loc)
        return c

    executions = 0
    while True:
        try:
            locals_: Dict[int, Any] = {}
            input_value = None
            have_input = False
            if input_chan is not None:
                input_value = input_chan.read()
                have_input = True

            def resolve(src_payload):
                src, payload = src_payload
                if src == SRC_CONST:
                    return payload
                if src == SRC_LOCAL:
                    v = locals_[payload]
                    if isinstance(v, _Poison):
                        raise v.err  # upstream error poisons this step
                    return v
                if src == SRC_CHAN:
                    return chan(payload).read()
                if src == SRC_INPUT:
                    if not have_input:
                        raise RuntimeError("plan uses input but none wired")
                    return input_value
                raise ValueError(src)

            for step in plan["steps"]:
                try:
                    args = [resolve(a) for a in step["args"]]
                    kwargs = {k: resolve(v) for k, v in step["kwargs"].items()}
                    out = getattr(instance, step["method"])(*args, **kwargs)
                except ChannelClosed:
                    raise
                except BaseException as e:  # noqa: BLE001 — error propagates
                    # through the graph, poisoning downstream stages
                    logger.debug("DAG step %s failed; poisoning "
                                 "downstream: %s", step["method"], e)
                    locals_[step["node_id"]] = _Poison(e)
                    for name in step["out_channels"]:
                        chan(name).write_error(e)
                    continue
                locals_[step["node_id"]] = out
                for name in step["out_channels"]:
                    chan(name).write(out, kind=KIND_DATA)
            executions += 1
            _mdefs.inc("rt_dag_execs_total")
        except ChannelClosed:
            # teardown: forward the sentinel so downstream loops exit too
            for step in plan["steps"]:
                for name in step["out_channels"]:
                    chan(name).close()
            return executions
        except BaseException:
            # channel-level failure (writer timeout, store error): the
            # loop cannot continue coherently — unblock downstream with
            # sentinels, then surface the error on the loop task itself
            for step in plan["steps"]:
                for name in step["out_channels"]:
                    chan(name).close()
            raise


class _Poison:
    """Marks a local value as an upstream error."""

    def __init__(self, err: BaseException):
        self.err = err

    def __repr__(self):
        return f"_Poison({self.err!r})"
