"""DAG authoring: .bind() graphs over actor methods.

Reference: `python/ray/dag/dag_node.py:29`, `input_node.py`,
`output_node.py` — `actor.method.bind(x)` builds a node instead of
executing; `with InputNode() as inp:` marks the per-execution input;
`MultiOutputNode([a, b])` returns multiple leaves.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_counter = itertools.count()


class DAGNode:
    def __init__(self):
        self._id = next(_node_counter)

    def _upstream(self) -> List["DAGNode"]:
        return []


class InputNode(DAGNode):
    """Per-execution input placeholder (reference: `dag/input_node.py`).
    Usable as a context manager for parity with the reference API."""

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc_info):
        return False


class ClassMethodNode(DAGNode):
    """One actor-method invocation in the graph (reference:
    `dag/class_node.py` ClassMethodNode)."""

    def __init__(self, actor_handle, method_name: str, args: Tuple,
                 kwargs: Dict):
        super().__init__()
        self.actor = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def _upstream(self) -> List[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def __repr__(self):
        return f"ClassMethodNode({self.method_name}#{self._id})"


class FunctionNode(DAGNode):
    """One remote-function invocation in a task DAG (reference:
    `dag/function_node.py`) — the node type workflows execute."""

    def __init__(self, remote_fn, args: Tuple, kwargs: Dict):
        super().__init__()
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs

    def _upstream(self) -> List[DAGNode]:
        ups = [a for a in self.args if isinstance(a, DAGNode)]
        ups += [v for v in self.kwargs.values() if isinstance(v, DAGNode)]
        return ups

    def execute(self, _memo: Optional[Dict[int, Any]] = None):
        """Eager recursive execution (reference: DAGNode.execute).
        Shared nodes (diamond DAGs) run exactly once per execute()."""
        import ray_tpu as rt

        memo: Dict[int, Any] = {} if _memo is None else _memo

        def resolve(v):
            if isinstance(v, FunctionNode):
                if v._id not in memo:
                    memo[v._id] = v.execute(memo)
                return memo[v._id]
            return v

        args = [resolve(a) for a in self.args]
        kwargs = {k: resolve(v) for k, v in self.kwargs.items()}
        return rt.get(self.remote_fn.remote(*args, **kwargs))

    def __repr__(self):
        name = getattr(self.remote_fn, "__name__", "fn")
        return f"FunctionNode({name}#{self._id})"


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one execute() result (reference:
    `dag/output_node.py`)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)

    def _upstream(self) -> List[DAGNode]:
        return list(self.outputs)

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)
