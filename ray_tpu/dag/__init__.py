"""Compiled actor DAGs (reference: `python/ray/dag/` +
`python/ray/experimental/channel/` — "accelerated DAGs").

Author with `actor.method.bind(...)` under a `with InputNode() as inp:`
block, compile with `.experimental_compile()`, then `execute()` per
input: data moves over shared-memory ring channels between resident
per-actor exec loops, bypassing the per-call submit/lease path.
"""

from ray_tpu.dag.channel import Channel, ChannelClosed
from ray_tpu.dag.compiled_dag import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    DAGNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "Channel",
    "ChannelClosed",
    "ClassMethodNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "InputNode",
    "MultiOutputNode",
]
