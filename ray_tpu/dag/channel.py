"""Shared-memory channels for compiled actor DAGs.

Reference: `python/ray/experimental/channel/shared_memory_channel.py:176`
backed by the native mutable-object manager
(`experimental_mutable_object_manager.h:48`, `WriteAcquire:153`) —
writer/reader acquire-release over one shm slot.  Here a channel is a
small ring of sealed store objects: write = create+seal of slot
`seq % ring`, read = blocking get + delete (the delete IS the release
that lets the writer reuse the slot).  Ring depth > 1 gives pipelined
executions backpressure-bounded exactly like the reference's buffered
channels.

Single-node scope (the compiled-graph fast path); cross-node stages fall
back to the ordinary actor-call path.
"""

from __future__ import annotations

import hashlib
import struct
import time
from typing import Any, Optional, Tuple

from ray_tpu.core import serialization as ser

# payload kinds
KIND_DATA = 0
KIND_ERROR = 1
KIND_SENTINEL = 2  # teardown marker, forwarded downstream

_RING = 8  # in-flight executions before writers block


class ChannelClosed(Exception):
    pass


class ChannelPollTimeout(Exception):
    """The blocking read expired with NOTHING consumed — distinct from a
    user-raised TimeoutError travelling as an error payload (which is
    consumed before it re-raises)."""


def _chan_hash(name: str) -> bytes:
    return hashlib.blake2b(name.encode(), digest_size=16).digest()


class Channel:
    """SPSC channel; open lazily in each endpoint process."""

    def __init__(self, name: str):
        self.name = name
        self._h = _chan_hash(name)
        self._read_seq = 0
        self._write_seq = 0

    def _store(self):
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().store

    def _key(self, seq: int) -> bytes:
        return self._h + struct.pack("<H", seq % 65536)

    # -- writer side ---------------------------------------------------
    def write(self, value: Any, kind: int = KIND_DATA,
              timeout_s: float = 120.0):
        store = self._store()
        seq = self._write_seq
        if seq >= _RING:
            # slot reuse: wait for the reader to release (delete) the
            # object written _RING executions ago
            old = self._key(seq - _RING)
            deadline = time.monotonic() + timeout_s
            while store.contains(old):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"channel {self.name}: reader lagging >{_RING} "
                        "executions behind"
                    )
                time.sleep(0.0002)
        if kind == KIND_DATA:
            payload = ser.serialize_to_bytes(value)
        elif kind == KIND_ERROR:
            payload = ser.serialize_to_bytes(value, tag=ser.TAG_ERROR)
        else:
            payload = b""
        store.put(self._key(seq), bytes([kind]) + bytes(payload))
        self._write_seq += 1

    def write_error(self, err: BaseException):
        self.write(err, kind=KIND_ERROR)

    def close(self):
        """Send the teardown sentinel."""
        try:
            self.write(None, kind=KIND_SENTINEL, timeout_s=5.0)
        except Exception:
            pass

    # -- reader side ---------------------------------------------------
    def read_raw(self, timeout_s: Optional[float] = None) -> Tuple[int, bytes]:
        store = self._store()
        key = self._key(self._read_seq)
        timeout_ms = -1 if timeout_s is None else max(1, int(timeout_s * 1000))
        try:
            view = store.get(key, timeout_ms=timeout_ms)
        except TimeoutError as e:
            raise ChannelPollTimeout(str(e)) from None
        try:
            data = bytes(view)
        finally:
            del view
            store.release(key)
            store.delete(key)
        self._read_seq += 1
        return data[0], data[1:]

    def read(self, timeout_s: Optional[float] = None) -> Any:
        kind, payload = self.read_raw(timeout_s)
        if kind == KIND_SENTINEL:
            raise ChannelClosed(self.name)
        tag, val = ser.deserialize(memoryview(payload))
        if tag == ser.TAG_ERROR:
            raise val if isinstance(val, BaseException) else RuntimeError(val)
        return val
