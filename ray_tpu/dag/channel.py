"""Shared-memory channels for compiled actor DAGs.

Reference: `python/ray/experimental/channel/shared_memory_channel.py:176`
backed by the native mutable-object manager
(`experimental_mutable_object_manager.h:48`, `WriteAcquire:153`) —
writer/reader acquire-release over fixed shm slots.

The fast path is the C++ mutable channel in `shm/shmstore.cc`
(`rts_chan_*`): a fixed ring of slots with a process-shared
mutex/condvar, ZERO allocation per message — write serializes straight
into the slot, publication is a sequence bump + broadcast, and the
reader's release hands the slot back (the same acquire/release protocol
as the reference's native channels).  Payloads larger than a slot fall
back to one store object per message; the slot then carries only the
object id.

Cross-node channels (reference:
`experimental_mutable_object_provider.h` — remote mutable objects):
the ring always lives on the READER's node; a writer on another node
relays writes through the daemons (`chan_remote_write`), which land in
the reader's local ring — the reader's hot path is identical either
way, and ring-full backpressure propagates to the remote writer through
the blocking daemon call.
"""

from __future__ import annotations

import hashlib
import struct
from typing import Any, Optional, Tuple

from ray_tpu.core import serialization as ser
from ray_tpu.shm import ChannelClosedError

# payload kinds (the ChanSlot.kind field)
KIND_DATA = 0
KIND_ERROR = 1
KIND_SENTINEL = 2  # teardown marker, forwarded downstream
KIND_SPILL_DATA = 3  # oversized: payload lives in a store object
KIND_SPILL_ERROR = 4

_RING = 8  # in-flight executions before writers block
_SLOT_BYTES = 128 * 1024  # inline payload budget per slot


class ChannelClosed(Exception):
    pass


class ChannelPollTimeout(Exception):
    """The blocking read expired with NOTHING consumed — distinct from a
    user-raised TimeoutError travelling as an error payload (which is
    consumed before it re-raises)."""


def _chan_hash(name: str) -> bytes:
    return hashlib.blake2b(name.encode(), digest_size=18).digest()


class Channel:
    """SPSC channel; open lazily in each endpoint process.

    `location` is the node id whose store hosts the ring (the reader's
    node).  None, or a location equal to the current process's node,
    means all ops are local; otherwise writes/close/destroy relay
    through the node daemons."""

    def __init__(self, name: str, location: Optional[str] = None):
        self.name = name
        self.location = location
        self._h = _chan_hash(name)
        # separate hash domain: a spill key must never collide with the
        # channel's own id (deleting it would destroy the live region)
        self._spill_h = hashlib.blake2b(
            (name + "/spill").encode(), digest_size=16
        ).digest()
        self._read_seq = 0
        self._write_seq = 0
        self._opened = False

    def _is_remote(self) -> bool:
        if self.location is None:
            return False
        from ray_tpu.core.runtime import get_runtime

        return self.location != get_runtime().node_id

    def _store(self):
        from ray_tpu.core.runtime import get_runtime

        store = get_runtime().store
        if not self._opened:
            store.chan_create(self._h, nslots=_RING, slot_size=_SLOT_BYTES)
            self._opened = True
        return store

    def _spill_key(self, seq: int) -> bytes:
        return self._spill_h + struct.pack("<H", seq % 65536)

    # -- writer side ---------------------------------------------------
    def write(self, value: Any, kind: int = KIND_DATA,
              timeout_s: float = 120.0):
        if kind == KIND_DATA:
            payload = ser.serialize_to_bytes(value)
        elif kind == KIND_ERROR:
            payload = ser.serialize_to_bytes(value, tag=ser.TAG_ERROR)
        else:
            payload = b""
        timeout_ms = max(1, int(timeout_s * 1000))
        if self._is_remote():
            self._remote_write(payload, kind, timeout_s, timeout_ms)
            self._write_seq += 1
            return
        store = self._store()
        try:
            if len(payload) <= _SLOT_BYTES:
                store.chan_write(self._h, payload, kind=kind,
                                 timeout_ms=timeout_ms)
            else:
                key = self._spill_key(self._write_seq)
                if store.contains(key):
                    store.delete(key)  # leftover from a failed attempt
                store.put(key, payload)
                spill_kind = (KIND_SPILL_ERROR if kind == KIND_ERROR
                              else KIND_SPILL_DATA)
                try:
                    store.chan_write(self._h, key, kind=spill_kind,
                                     timeout_ms=timeout_ms)
                except Exception:
                    store.delete(key)  # unpublished: reclaim it
                    raise
        except ChannelClosedError:
            raise ChannelClosed(self.name) from None
        except TimeoutError:
            raise TimeoutError(
                f"channel {self.name}: reader lagging >{_RING} "
                "executions behind"
            ) from None
        self._write_seq += 1

    def _remote_write(self, payload: bytes, kind: int,
                      timeout_s: float, timeout_ms: int):
        """Relay a write to the ring on `location` through the node
        daemons.  The daemon-side chan write blocks (in a worker
        thread) while the remote ring is full, so backpressure reaches
        this writer through the pending reply."""
        from ray_tpu.core.runtime import get_runtime

        spill_key = (
            self._spill_key(self._write_seq)
            if len(payload) > _SLOT_BYTES else None
        )
        reply = get_runtime().noded_call(
            "chan_remote_write",
            {
                "node_id": self.location,
                "chan": self._h,
                "kind": kind,
                "payload": payload,
                "spill_key": spill_key,
                "timeout_ms": timeout_ms,
            },
            timeout=timeout_s + 30,
        )
        status = (reply or {}).get("status", "error")
        if status == "ok":
            return
        if status == "closed":
            raise ChannelClosed(self.name)
        if status == "timeout":
            raise TimeoutError(
                f"channel {self.name}: reader lagging >{_RING} "
                "executions behind"
            )
        raise RuntimeError(
            f"remote channel write failed: {(reply or {}).get('error')}"
        )

    def write_error(self, err: BaseException):
        self.write(err, kind=KIND_ERROR)

    def close(self):
        """Send the teardown sentinel, then mark the ring closed (the
        reader drains published messages before seeing closed)."""
        try:
            self.write(None, kind=KIND_SENTINEL, timeout_s=5.0)
        except Exception:
            pass
        try:
            if self._is_remote():
                self._remote_ring_op("chan_remote_close")
            else:
                self._store().chan_close(self._h)
        except Exception:
            pass

    def _remote_ring_op(self, method: str):
        from ray_tpu.core.runtime import get_runtime

        get_runtime().noded_call(
            method, {"node_id": self.location, "chan": self._h}, timeout=30
        )

    def destroy(self):
        """Free the channel's pinned shm region.  Called at DAG
        teardown AFTER the endpoints exited — channels are allocated
        non-evictable, so without this every compiled DAG would leak
        arena permanently."""
        if self._is_remote():
            try:
                self._remote_ring_op("chan_remote_destroy")
            except Exception:
                pass
            return
        from ray_tpu.core.runtime import get_runtime

        store = get_runtime().store
        try:
            store.chan_close(self._h)
        except Exception:
            pass
        try:
            store.chan_delete(self._h)
        except Exception:
            pass

    # -- reader side ---------------------------------------------------
    def read_raw(self, timeout_s: Optional[float] = None) -> Tuple[int, bytes]:
        if self._is_remote():
            raise RuntimeError(
                f"channel {self.name}: ring lives on node "
                f"{self.location}; only that node's processes may read"
            )
        store = self._store()
        timeout_ms = -1 if timeout_s is None else max(1, int(timeout_s * 1000))
        try:
            kind, data = store.chan_read(self._h, timeout_ms=timeout_ms)
        except TimeoutError as e:
            raise ChannelPollTimeout(str(e)) from None
        except ChannelClosedError:
            raise ChannelClosed(self.name) from None
        if kind in (KIND_SPILL_DATA, KIND_SPILL_ERROR):
            key = bytes(data)
            view = store.get(key, timeout_ms=timeout_ms)
            try:
                data = bytes(view)
            finally:
                del view
                store.release(key)
                store.delete(key)
            kind = KIND_ERROR if kind == KIND_SPILL_ERROR else KIND_DATA
        self._read_seq += 1
        return kind, data

    def read(self, timeout_s: Optional[float] = None) -> Any:
        kind, payload = self.read_raw(timeout_s)
        if kind == KIND_SENTINEL:
            raise ChannelClosed(self.name)
        tag, val = ser.deserialize(memoryview(payload))
        if tag == ser.TAG_ERROR:
            raise val if isinstance(val, BaseException) else RuntimeError(val)
        return val
