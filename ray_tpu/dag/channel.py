"""Shared-memory channels for compiled actor DAGs.

Reference: `python/ray/experimental/channel/shared_memory_channel.py:176`
backed by the native mutable-object manager
(`experimental_mutable_object_manager.h:48`, `WriteAcquire:153`) —
writer/reader acquire-release over fixed shm slots.

The fast path is the C++ mutable channel in `shm/shmstore.cc`
(`rts_chan_*`): a fixed ring of slots with a process-shared
mutex/condvar, ZERO allocation per message — write serializes straight
into the slot, publication is a sequence bump + broadcast, and the
reader's release hands the slot back (the same acquire/release protocol
as the reference's native channels).  Payloads larger than a slot fall
back to one store object per message; the slot then carries only the
object id.

Tensor payloads (`KIND_TENSOR`) skip pickle entirely: a compact
struct-packed header (dtype/shape/sharding per tensor, container kind,
optional small metadata blob) is followed by the raw array buffers,
written straight into the slot as one publication — multi-output steps
therefore batch into a single slot write.  The reader adopts the bytes
back into `jax.Array`s / numpy views without a pickle round trip.  The
header carries sharding metadata and a handle kind so an ICI
device-to-device channel can slot in later (`HANDLE_DEVICE`, SURVEY §7:
objects carry sharding metadata + buffer handles); this shm path is the
host fallback that CPU tier-1 exercises.

Cross-node channels (reference:
`experimental_mutable_object_provider.h` — remote mutable objects):
the ring always lives on the READER's node; a writer on another node
relays writes through the daemons (`chan_remote_write`), which land in
the reader's local ring — the reader's hot path is identical either
way, and ring-full backpressure propagates to the remote writer through
the blocking daemon call.

Ring geometry comes from the config (`dag_ring_slots` /
`RT_DAG_RING_SLOTS`, `dag_slot_bytes` / `RT_DAG_SLOT_BYTES`), validated
at channel creation; per-channel overrides cover special shapes (the
1F1B pipeline's double-buffered activation rings).
"""

from __future__ import annotations

import hashlib
import logging
import struct
import sys
import time
from typing import Any, List, Optional, Tuple

import ray_tpu.shm as _shm
from ray_tpu.core import serialization as ser
from ray_tpu.core.config import get_config
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.shm import ChannelClosedError

logger = logging.getLogger(__name__)

# payload kinds (the ChanSlot.kind field)
KIND_DATA = 0
KIND_ERROR = 1
KIND_SENTINEL = 2  # teardown marker, forwarded downstream
KIND_SPILL_DATA = 3  # oversized: payload lives in a store object
KIND_SPILL_ERROR = 4
KIND_TENSOR = 5  # header + raw array buffers, no pickle
KIND_SPILL_TENSOR = 6  # tensor payload spilled to a store object

# slot kind -> its spilled twin (and back); the daemon relay uses the
# same mapping when an oversized remote write lands on the reader node
SPILL_KIND = {
    KIND_DATA: KIND_SPILL_DATA,
    KIND_ERROR: KIND_SPILL_ERROR,
    KIND_TENSOR: KIND_SPILL_TENSOR,
}
INLINE_KIND = {v: k for k, v in SPILL_KIND.items()}

# tensor-header handle kinds: where the buffer bytes live.  DEVICE is
# reserved for a future ICI device-to-device channel — the header
# already carries the sharding metadata such a channel needs; this shm
# path is the host fallback.
HANDLE_INLINE = 0  # raw bytes follow the header in the same slot
HANDLE_STORE = 1  # raw bytes live in one store object (spill)
HANDLE_DEVICE = 2  # reserved: device buffer handle (ICI channels)

_CONT_SINGLE = 0
_CONT_TUPLE = 1
_CONT_LIST = 2
_CONT_DICT = 3

_TENSOR_VERSION = 1
_ALIGN = 64


class ChannelClosed(Exception):
    pass


class ChannelPollTimeout(Exception):
    """The blocking read expired with NOTHING consumed — distinct from a
    user-raised TimeoutError travelling as an error payload (which is
    consumed before it re-raises)."""


def ring_geometry(ring_slots: Optional[int] = None,
                  slot_bytes: Optional[int] = None) -> Tuple[int, int]:
    """Resolve and VALIDATE channel geometry: explicit overrides win,
    else the config knobs (`RT_DAG_RING_SLOTS` / `RT_DAG_SLOT_BYTES`).
    Raises ValueError at channel creation rather than letting a bad
    knob surface as a cryptic native-ring failure mid-execution."""
    cfg = get_config()
    slots = int(cfg.dag_ring_slots if ring_slots is None else ring_slots)
    size = int(cfg.dag_slot_bytes if slot_bytes is None else slot_bytes)
    if not 2 <= slots <= 4096:
        raise ValueError(
            f"dag_ring_slots (RT_DAG_RING_SLOTS) must be in [2, 4096], "
            f"got {slots} — 1 slot cannot double-buffer and huge rings "
            "pin arena forever"
        )
    if not 1024 <= size <= 256 * 1024 * 1024:
        raise ValueError(
            f"dag_slot_bytes (RT_DAG_SLOT_BYTES) must be in [1 KiB, "
            f"256 MiB], got {size}"
        )
    return slots, size


def _chan_hash(name: str) -> bytes:
    return hashlib.blake2b(name.encode(), digest_size=18).digest()


# -- tensor codec ------------------------------------------------------
_codec_dtype_memo: dict = {}


def _codec_dtype_ok(dt) -> bool:
    """Can this dtype round-trip through the raw-bytes codec?  Plain
    numeric/bool kinds always do; extended dtypes (bfloat16, fp8 — numpy
    kind 'V' but resolvable by name) are probed once and memoized.
    Structured/object/string dtypes fall back to the pickle path."""
    ok = _codec_dtype_memo.get(dt)
    if ok is None:
        if dt.names is not None or dt.kind in "OUSMm":
            ok = False  # structured/object/string/datetime: pickle path
        elif dt.kind in "biufc":
            ok = True
        else:
            try:  # name-resolvable extended dtype?
                ok = _np_dtype(str(dt)) == dt and len(str(dt)) < 256
            except Exception as e:
                logger.debug("dtype %s takes the pickle path: %s", dt, e)
                ok = False
        _codec_dtype_memo[dt] = ok
    return ok


def _is_tensor(x: Any) -> bool:
    import numpy as np

    if isinstance(x, np.ndarray):
        return _codec_dtype_ok(x.dtype)
    if "jax" in sys.modules:
        import jax

        if isinstance(x, jax.Array):
            try:
                dt = np.dtype(x.dtype)  # extended dtypes (PRNG keys,
                # quantization scales) raise TypeError: pickle path
            except TypeError:
                return False
            # a non-fully-addressable array cannot be materialized to
            # host bytes here — it stays on the pickle path too
            return (_codec_dtype_ok(dt)
                    and getattr(x, "is_fully_addressable", True))
    return False


def as_tensor_batch(value: Any):
    """(container, keys, arrays) when `value` is a pure tensor payload
    — a single array, or an EXACT builtin tuple/list/str-keyed dict of
    them — else None (the payload takes the pickle path).  Subclasses
    (NamedTuple, OrderedDict, ...) deliberately stay on pickle: the
    codec reconstructs builtin containers only, and silently degrading
    a typed container would break its consumers."""
    if _is_tensor(value):
        return _CONT_SINGLE, None, [value]
    if type(value) in (tuple, list) and value and all(
        _is_tensor(v) for v in value
    ):
        cont = _CONT_TUPLE if type(value) is tuple else _CONT_LIST
        return cont, None, list(value)
    if (
        type(value) is dict
        and value
        and all(isinstance(k, str) for k in value)
        and all(_is_tensor(v) for v in value.values())
    ):
        return _CONT_DICT, list(value.keys()), list(value.values())
    return None


def _sharding_blob(arr: Any) -> bytes:
    """Compact JSON description of a jax.Array's sharding (mesh axis
    sizes + partition spec), carried so a device channel can reproduce
    the layout; empty for host arrays / single-device default."""
    sh = getattr(arr, "sharding", None)
    if sh is None:
        return b""
    try:
        mesh = getattr(sh, "mesh", None)
        spec = getattr(sh, "spec", None)
        if mesh is None or spec is None:
            return b""
        axes = dict(getattr(mesh, "shape", {}) or {})
        if not axes or all(v == 1 for v in axes.values()):
            return b""
        import json

        return json.dumps(
            {"mesh": axes, "spec": [None if p is None else p for p in spec]}
        ).encode()
    except Exception as e:  # best-effort metadata, never blocks the send
        logger.debug("sharding metadata skipped for %r: %s", type(arr), e)
        return b""


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax's extended dtypes (bfloat16, fp8)

        return np.dtype(getattr(ml_dtypes, name))


def encode_tensors(value: Any, extra: Any = None,
                   handle_kind: int = HANDLE_INLINE
                   ) -> Tuple[List[Any], int]:
    """Encode a tensor batch to (chunks, total_bytes): a struct-packed
    header chunk followed by 64-byte-aligned raw buffers.  `extra` is a
    small control-plane blob (pickled; e.g. the rllib sample meta) —
    the ARRAY bytes never see pickle."""
    import numpy as np

    tb = as_tensor_batch(value)
    if tb is None:
        raise TypeError(
            f"not a tensor payload: {type(value)} (need array, "
            "tuple/list of arrays, or str-keyed dict of arrays)"
        )
    container, keys, arrays = tb
    extra_b = ser.dumps_oob(extra) if extra is not None else b""
    head = bytearray()
    head += struct.pack("<BBBBHI", _TENSOR_VERSION, container, handle_kind,
                        0, len(arrays), len(extra_b))
    head += extra_b
    bufs: List[Any] = []
    for i, arr in enumerate(arrays):
        is_jax = not isinstance(arr, np.ndarray)
        shard = _sharding_blob(arr) if is_jax else b""
        host = np.asarray(arr)
        if not host.flags["C_CONTIGUOUS"]:
            host = np.ascontiguousarray(host)
        dt = str(host.dtype).encode()
        key = keys[i].encode() if container == _CONT_DICT else b""
        head += struct.pack("<BBBBHHQ", 1 if is_jax else 0, len(dt),
                            host.ndim, 0, len(key), len(shard),
                            host.nbytes)
        head += dt + key + shard
        head += struct.pack(f"<{host.ndim}Q", *host.shape)
        bufs.append(host)
    chunks: List[Any] = [bytes(head)]
    pos = len(head)
    for host in bufs:
        pad = (-pos) % _ALIGN
        if pad:
            chunks.append(b"\x00" * pad)
            pos += pad
        try:
            view = memoryview(host).cast("B")
        except (ValueError, TypeError):
            # extended dtypes (bfloat16, fp8) refuse the buffer
            # protocol; a flat uint8 view exposes the same bytes
            view = memoryview(host.reshape(-1).view(np.uint8))
        chunks.append(view)
        pos += view.nbytes
    return chunks, pos


def parse_tensor_header(buf: memoryview):
    """Walk a KIND_TENSOR payload's header.  Returns (container, extra,
    entries, buffers_start) where each entry is a dict with key/dtype/
    shape/is_jax/sharding/nbytes/offset — the offsets index into `buf`.
    Exposed for tests and the future device-channel adopt path."""
    buf = memoryview(buf).cast("B")
    ver, container, handle_kind, _, n, extra_len = struct.unpack_from(
        "<BBBBHI", buf, 0
    )
    if ver != _TENSOR_VERSION:
        raise ValueError(f"unknown tensor header version {ver}")
    pos = struct.calcsize("<BBBBHI")
    extra = ser.loads(buf[pos:pos + extra_len]) if extra_len else None
    pos += extra_len
    entries = []
    for _ in range(n):
        is_jax, dt_len, ndim, _, key_len, shard_len, nbytes = (
            struct.unpack_from("<BBBBHHQ", buf, pos)
        )
        pos += struct.calcsize("<BBBBHHQ")
        dtype = bytes(buf[pos:pos + dt_len]).decode()
        pos += dt_len
        key = bytes(buf[pos:pos + key_len]).decode() if key_len else None
        pos += key_len
        shard = bytes(buf[pos:pos + shard_len]).decode() if shard_len else ""
        pos += shard_len
        shape = struct.unpack_from(f"<{ndim}Q", buf, pos)
        pos += 8 * ndim
        entries.append({
            "key": key, "dtype": dtype, "shape": tuple(shape),
            "is_jax": bool(is_jax), "sharding": shard, "nbytes": nbytes,
        })
    head_end = pos
    off = head_end
    for e in entries:
        off += (-off) % _ALIGN
        e["offset"] = off
        off += e["nbytes"]
    return container, extra, entries, head_end


def decode_tensors(buf: memoryview) -> Tuple[Any, Any]:
    """Adopt a KIND_TENSOR payload back into arrays: numpy entries come
    back as READ-ONLY views over the message bytes (the zero-copy
    contract — a consumer that mutates in place must `.copy()` first),
    jax entries are adopted into `jax.Array`s via the host buffer (the
    device copy the eventual ICI channel elides).  Returns
    (value, extra)."""
    import numpy as np

    buf = memoryview(buf).cast("B")
    container, extra, entries, _ = parse_tensor_header(buf)
    arrays = []
    for e in entries:
        host = np.frombuffer(
            buf[e["offset"]:e["offset"] + e["nbytes"]],
            dtype=_np_dtype(e["dtype"]),
        ).reshape(e["shape"])
        if e["is_jax"]:
            import jax.numpy as jnp

            arrays.append(jnp.asarray(host))
        else:
            arrays.append(host)
    if container == _CONT_SINGLE:
        value: Any = arrays[0]
    elif container == _CONT_TUPLE:
        value = tuple(arrays)
    elif container == _CONT_LIST:
        value = arrays
    else:
        value = {e["key"]: a for e, a in zip(entries, arrays)}
    return value, extra


class Channel:
    """SPSC channel; open lazily in each endpoint process.

    `location` is the node id whose store hosts the ring (the reader's
    node).  None, or a location equal to the current process's node,
    means all ops are local; otherwise writes/close/destroy relay
    through the node daemons."""

    def __init__(self, name: str, location: Optional[str] = None,
                 ring_slots: Optional[int] = None,
                 slot_bytes: Optional[int] = None):
        self.name = name
        self.location = location
        self.ring_slots, self.slot_bytes = ring_geometry(
            ring_slots, slot_bytes
        )
        self._h = _chan_hash(name)
        # separate hash domain: a spill key must never collide with the
        # channel's own id (deleting it would destroy the live region)
        self._spill_h = hashlib.blake2b(
            (name + "/spill").encode(), digest_size=16
        ).digest()
        self._read_seq = 0
        self._write_seq = 0
        self._opened = False

    def _is_remote(self) -> bool:
        if self.location is None:
            return False
        from ray_tpu.core.runtime import get_runtime

        return self.location != get_runtime().node_id

    def _store(self):
        from ray_tpu.core.runtime import get_runtime

        store = get_runtime().store
        if not self._opened:
            store.chan_create(self._h, nslots=self.ring_slots,
                              slot_size=self.slot_bytes)
            self._opened = True
        return store

    def _spill_key(self, seq: int) -> bytes:
        return self._spill_h + struct.pack("<H", seq % 65536)

    # -- writer side ---------------------------------------------------
    def _slot_publish(self, store, chunks: List[Any], kind: int,
                      timeout_ms: int):
        """One slot publication, with ring-full accounting: when
        metrics are on, a short first acquire distinguishes "slot free"
        from "ring full, we blocked" without changing the blocking
        semantics the disabled path keeps."""
        if _mdefs.enabled() and (timeout_ms < 0 or timeout_ms > 25):
            try:
                store.chan_write_chunks(self._h, chunks, kind=kind,
                                        timeout_ms=25)
                return
            except TimeoutError:
                _mdefs.inc("rt_dag_channel_ring_full_total")
                remaining = timeout_ms if timeout_ms < 0 else timeout_ms - 25
                store.chan_write_chunks(self._h, chunks, kind=kind,
                                        timeout_ms=max(1, remaining)
                                        if remaining >= 0 else -1)
                return
        store.chan_write_chunks(self._h, chunks, kind=kind,
                                timeout_ms=timeout_ms)

    def _write_chunks(self, chunks: List[Any], total: int, kind: int,
                      timeout_s: float):
        """Local-ring publication of an encoded payload: inline when it
        fits the slot, else raw bytes go to ONE store object and the
        slot carries only the key (same spill rule as pickle payloads,
        so tensor batches of any size ride the same channel)."""
        timeout_ms = max(1, int(timeout_s * 1000))
        store = self._store()
        t0 = time.perf_counter()
        try:
            if total <= self.slot_bytes:
                self._slot_publish(store, chunks, kind, timeout_ms)
            else:
                key = self._spill_key(self._write_seq)
                if store.contains(key):
                    store.delete(key)  # leftover from a failed attempt
                buf = store.create(key, total)
                try:
                    pos = 0
                    for c in chunks:
                        v = memoryview(c).cast("B")
                        buf[pos:pos + v.nbytes] = v
                        pos += v.nbytes
                except BaseException:
                    del buf
                    store.abort(key)  # partial create must not leak
                    raise
                del buf
                store.seal(key)
                try:
                    self._slot_publish(store, [key], SPILL_KIND[kind],
                                       timeout_ms)
                except Exception:
                    store.delete(key)  # unpublished: reclaim it
                    raise
        except ChannelClosedError:
            raise ChannelClosed(self.name) from None
        except TimeoutError:
            raise TimeoutError(
                f"channel {self.name}: reader lagging >{self.ring_slots} "
                "messages behind"
            ) from None
        finally:
            _mdefs.observe("rt_dag_channel_write_seconds",
                           time.perf_counter() - t0)
        self._write_seq += 1

    def write(self, value: Any, kind: int = KIND_DATA,
              timeout_s: float = 120.0):
        if kind == KIND_DATA and as_tensor_batch(value) is not None:
            return self.write_tensors(value, timeout_s=timeout_s)
        if kind == KIND_DATA:
            payload = ser.serialize_to_bytes(value)
        elif kind == KIND_ERROR:
            payload = ser.serialize_to_bytes(value, tag=ser.TAG_ERROR)
        else:
            payload = b""
        if self._is_remote():
            self._remote_write(payload, kind, timeout_s)
            self._write_seq += 1
            return
        self._write_chunks([payload], len(payload), kind, timeout_s)

    def write_tensors(self, value: Any, extra: Any = None,
                      timeout_s: float = 120.0):
        """Publish a tensor batch (array / tuple / list / dict of
        arrays) without pickling the array bytes; `extra` carries a
        small metadata blob alongside (read back by read_tensors)."""
        chunks, total = encode_tensors(value, extra)
        if self._is_remote():
            # relay path: assemble once (the bytes cross a socket
            # anyway) and let the reader-side daemon spill if oversized
            payload = b"".join(
                bytes(c) if not isinstance(c, bytes) else c for c in chunks
            )
            self._remote_write(payload, KIND_TENSOR, timeout_s)
            self._write_seq += 1
            return
        self._write_chunks(chunks, total, KIND_TENSOR, timeout_s)

    def _remote_write(self, payload: bytes, kind: int, timeout_s: float):
        """Relay a write to the ring on `location` through the node
        daemons.  The daemon-side chan write blocks (in a worker
        thread) while the remote ring is full, so backpressure reaches
        this writer through the pending reply."""
        from ray_tpu.core.runtime import get_runtime

        timeout_ms = max(1, int(timeout_s * 1000))
        spill_key = (
            self._spill_key(self._write_seq)
            if len(payload) > self.slot_bytes else None
        )
        t0 = time.perf_counter()
        reply = get_runtime().noded_call(
            "chan_remote_write",
            {
                "node_id": self.location,
                "chan": self._h,
                "kind": kind,
                "payload": payload,
                "spill_key": spill_key,
                "timeout_ms": timeout_ms,
                "ring_slots": self.ring_slots,
                "slot_bytes": self.slot_bytes,
            },
            timeout=timeout_s + 30,
        )
        _mdefs.observe("rt_dag_channel_write_seconds",
                       time.perf_counter() - t0)
        status = (reply or {}).get("status", "error")
        if status == "ok":
            return
        if status == "closed":
            raise ChannelClosed(self.name)
        if status == "timeout":
            _mdefs.inc("rt_dag_channel_ring_full_total")
            raise TimeoutError(
                f"channel {self.name}: reader lagging >{self.ring_slots} "
                "messages behind"
            )
        raise RuntimeError(
            f"remote channel write failed: {(reply or {}).get('error')}"
        )

    def write_error(self, err: BaseException):
        self.write(err, kind=KIND_ERROR)

    def close(self):
        """Send the teardown sentinel, then mark the ring closed (the
        reader drains published messages before seeing closed)."""
        try:
            self.write(None, kind=KIND_SENTINEL, timeout_s=5.0)
        except Exception as e:
            # full-ring/dead-reader sentinels are best effort; the
            # closed mark below still unblocks both endpoints
            logger.debug("channel %s: close sentinel skipped: %s",
                         self.name, e)
        try:
            if self._is_remote():
                self._remote_ring_op("chan_remote_close")
            else:
                self._store().chan_close(self._h)
        except Exception as e:
            logger.debug("channel %s: close failed: %s", self.name, e)

    def _remote_ring_op(self, method: str):
        from ray_tpu.core.runtime import get_runtime

        get_runtime().noded_call(
            method, {"node_id": self.location, "chan": self._h}, timeout=30
        )

    def destroy(self):
        """Free the channel's pinned shm region.  Called at DAG
        teardown AFTER the endpoints exited — channels are allocated
        non-evictable, so without this every compiled DAG would leak
        arena permanently."""
        if self._is_remote():
            try:
                self._remote_ring_op("chan_remote_destroy")
            except Exception as e:
                logger.debug("channel %s: remote destroy failed: %s",
                             self.name, e)
            return
        from ray_tpu.core.runtime import get_runtime

        store = get_runtime().store
        try:
            store.chan_close(self._h)
        except Exception as e:
            logger.debug("channel %s: close-at-destroy failed: %s",
                         self.name, e)
        try:
            store.chan_delete(self._h)
        except Exception as e:
            logger.debug("channel %s: delete failed: %s", self.name, e)

    # -- reader side ---------------------------------------------------
    def read_raw(self, timeout_s: Optional[float] = None) -> Tuple[int, bytes]:
        if self._is_remote():
            raise RuntimeError(
                f"channel {self.name}: ring lives on node "
                f"{self.location}; only that node's processes may read"
            )
        store = self._store()
        timeout_ms = -1 if timeout_s is None else max(1, int(timeout_s * 1000))
        try:
            kind, data = store.chan_read(self._h, timeout_ms=timeout_ms)
        except TimeoutError as e:
            raise ChannelPollTimeout(str(e)) from None
        except ChannelClosedError:
            raise ChannelClosed(self.name) from None
        if kind in INLINE_KIND:
            key = bytes(data)
            view = store.get(key, timeout_ms=timeout_ms)
            try:
                data = bytes(view)
            finally:
                del view
                store.release(key)
                store.delete(key)
            kind = INLINE_KIND[kind]
        self._read_seq += 1
        return kind, data

    def _decode(self, kind: int, payload: bytes) -> Tuple[Any, Any]:
        if kind == KIND_SENTINEL:
            raise ChannelClosed(self.name)
        if kind == _shm.KIND_OVERFLOW_MARKER:
            raise RuntimeError(
                f"channel {self.name}: writer overflowed the slot "
                f"(endpoint ring geometries disagree — the creator's "
                "RT_DAG_SLOT_BYTES won); message dropped"
            )
        if kind == KIND_TENSOR:
            return decode_tensors(memoryview(payload))
        tag, val = ser.deserialize(memoryview(payload))
        if tag == ser.TAG_ERROR:
            raise val if isinstance(val, BaseException) else RuntimeError(val)
        return val, None

    def read(self, timeout_s: Optional[float] = None) -> Any:
        kind, payload = self.read_raw(timeout_s)
        return self._decode(kind, payload)[0]

    def read_tensors(self, timeout_s: Optional[float] = None
                     ) -> Tuple[Any, Any]:
        """Like read(), but returns (value, extra) so tensor payloads
        hand back the metadata blob their writer attached."""
        kind, payload = self.read_raw(timeout_s)
        return self._decode(kind, payload)
