"""LLM serving example: a Llama replica behind serve (BASELINE #5).

Reference capability: "Ray Serve Llama-3 8B JAX replica (autoscaled TPU
deployment)" — a deployment hosting a jax Llama with KV-cached decoding
(`models/llama.py` prefill/decode_step/generate), dynamic request
batching (`@serve.batch` — batches compile once per shape and reuse the
program, the TPU-native win), and serve autoscaling from queue metrics.

Token-id interface (no tokenizer dependency in-image): POST
`{"tokens": [[1,2,3,...]], "max_new_tokens": 16}` -> generated ids.

    from ray_tpu.examples.serve_llm import run
    handle = run(model_size="tiny")          # or "llama2_7b"/"llama3_8b"
    out = handle.generate.remote([[1, 2, 3]]).result()
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import List, Optional

from ray_tpu import serve

MODEL_SIZES = ("tiny", "llama2_7b", "llama3_8b")


@serve.deployment(
    max_ongoing_requests=32,
    autoscaling_config={"min_replicas": 1, "max_replicas": 2,
                        "target_ongoing_requests": 16},
)
class LlamaService:
    """One replica = one model instance on this host's chips.

    Scaling out is serve autoscaling (more replicas); scaling up is a
    mesh passed to the model (tp/sp sharding rules) — the single-replica
    path here keeps the example self-contained.
    """

    def __init__(self, model_size: str = "tiny", max_new_tokens: int = 16,
                 seed: int = 0, max_batch_size: int = 8):
        import jax

        from ray_tpu.models import llama

        if model_size not in MODEL_SIZES:
            raise ValueError(f"model_size must be one of {MODEL_SIZES}")
        self._llama = llama
        self.cfg = {
            "tiny": llama.LlamaConfig.tiny,
            "llama2_7b": llama.LlamaConfig.llama2_7b,
            "llama3_8b": llama.LlamaConfig.llama3_8b,
        }[model_size]()
        self.params = llama.init_params(self.cfg, jax.random.PRNGKey(seed))
        self.max_new_tokens = max_new_tokens
        # instance-level batching config consumed by @serve.batch
        self.__serve_batch_overrides__ = {
            "_generate_batch": {"max_batch_size": max_batch_size},
        }

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, requests: List[dict]) -> List[List[int]]:
        """Batched generation.  Prompts are grouped by length so each
        group is one [B, T] generate call — XLA compiles per shape, and
        same-shape batches reuse the compiled prefill/decode programs."""
        import asyncio

        import jax.numpy as jnp

        def _run_groups():
            out: List[Optional[List[int]]] = [None] * len(requests)
            groups = defaultdict(list)
            for i, req in enumerate(requests):
                groups[(len(req["tokens"]), req["max_new_tokens"])].append(i)
            for (T, n_new), idxs in groups.items():
                arr = jnp.asarray(
                    [requests[i]["tokens"] for i in idxs], jnp.int32
                )
                gen = self._llama.generate(
                    self.cfg, self.params, arr, n_new, temperature=0.0
                )
                for j, i in enumerate(idxs):
                    out[i] = [int(t) for t in gen[j]]
            return out

        # the decode loop blocks (per-token device syncs): run it on
        # the worker pool so the replica's event loop keeps gathering
        # batches and serving health checks
        from ray_tpu.core.runtime import get_runtime

        return await asyncio.get_running_loop().run_in_executor(
            get_runtime()._exec_pool, _run_groups
        )

    async def generate(self, token_lists: List[List[int]],
                       max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Python-handle surface: a list of prompts (token ids)."""
        import asyncio

        n_new = (max_new_tokens if max_new_tokens is not None
                 else self.max_new_tokens)
        return list(await asyncio.gather(*[
            self._generate_batch({"tokens": toks, "max_new_tokens": n_new})
            for toks in token_lists
        ]))

    async def __call__(self, request):
        body = request.json() if request.body() else {}
        tokens = body["tokens"]
        n_new = int(body.get("max_new_tokens", self.max_new_tokens))
        result = await self.generate(tokens, n_new)
        return {"tokens": result}


def build_app(model_size: str = "tiny", max_new_tokens: int = 16):
    return LlamaService.bind(model_size=model_size,
                             max_new_tokens=max_new_tokens)


def run(model_size: str = "tiny", max_new_tokens: int = 16,
        name: str = "llm", route_prefix: str = "/llm",
        timeout_s: float = 300.0):
    """Deploy and return the app handle.  The ready timeout covers a
    cold replica init on real chips (first jax/TPU init in a fresh
    worker is tens of seconds; big-model weight init longer)."""
    return serve.run(
        build_app(model_size, max_new_tokens),
        name=name, route_prefix=route_prefix, timeout_s=timeout_s,
    )
