"""LLM serving example: a Llama replica behind serve (BASELINE #5).

Reference capability: "Ray Serve Llama-3 8B JAX replica (autoscaled TPU
deployment)" — a deployment hosting a jax Llama with KV-cached decoding
(`models/llama.py` prefill/decode_step/generate), dynamic request
batching (`@serve.batch` — batches compile once per shape and reuse the
program, the TPU-native win), and serve autoscaling from queue metrics.

Token-id interface (no tokenizer dependency in-image): POST
`{"tokens": [[1,2,3,...]], "max_new_tokens": 16}` -> generated ids.

    from ray_tpu.examples.serve_llm import run
    handle = run(model_size="tiny")          # or "llama2_7b"/"llama3_8b"
    out = handle.generate.remote([[1, 2, 3]]).result()
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import List, Optional

from ray_tpu import serve

MODEL_SIZES = ("tiny", "llama1b4", "llama2_7b", "llama3_8b")


def _build_model(model_size: str, seed: int):
    """Shared (cfg, params) constructor for both deployments: one
    place owns the size table and the bf16 serving cast."""
    import jax

    from ray_tpu.models import llama

    if model_size not in MODEL_SIZES:
        raise ValueError(f"model_size must be one of {MODEL_SIZES}")
    cfg = {
        "tiny": llama.LlamaConfig.tiny,
        # the per-chip serving unit for a 16 GB v5e-1 (same 1.4B
        # class as the llama_lora train bench); bigger models shard
        # over a mesh, the replica stays the per-host unit
        "llama1b4": lambda: llama.LlamaConfig(
            vocab_size=32000, max_seq_len=1024, dim=2048, n_layers=22,
            n_heads=16, n_kv_heads=16, intermediate=5632,
        ),
        "llama2_7b": llama.LlamaConfig.llama2_7b,
        "llama3_8b": llama.LlamaConfig.llama3_8b,
    }[model_size]()
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    if model_size != "tiny":
        # serving decode is weight-read bound: bf16 weights halve
        # HBM footprint and double effective decode bandwidth
        import jax.numpy as jnp

        params = jax.tree.map(lambda p: p.astype(jnp.bfloat16), params)
    return cfg, params


def _bench_generate(cfg, params, batch: int, prompt_len: int,
                    max_new_tokens: int, iters: int) -> dict:
    """Bare `llama.generate` timing in the calling process — the
    no-serve baseline both deployments' bench_direct expose; one body
    so the overhead metric can never desynchronize between them."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import llama

    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (batch, prompt_len), 0,
        cfg.vocab_size, dtype=jnp.int32,
    )
    np.asarray(llama.generate(
        cfg, params, prompt, max_new_tokens
    ))  # warmup: compiles prefill + decode; host read = sync
    t0 = time.perf_counter()
    for _ in range(iters):
        np.asarray(llama.generate(cfg, params, prompt, max_new_tokens))
    dt = time.perf_counter() - t0
    return {
        "tokens_per_sec": batch * max_new_tokens * iters / dt,
        "seconds_per_iter": dt / iters,
        "batch": batch,
    }



@serve.deployment(
    max_ongoing_requests=32,
    autoscaling_config={"min_replicas": 1, "max_replicas": 2,
                        "target_ongoing_requests": 16},
)
class LlamaService:
    """One replica = one model instance on this host's chips.

    Scaling out is serve autoscaling (more replicas); scaling up is a
    mesh passed to the model (tp/sp sharding rules) — the single-replica
    path here keeps the example self-contained.
    """

    def __init__(self, model_size: str = "tiny", max_new_tokens: int = 16,
                 seed: int = 0, max_batch_size: int = 8,
                 bucket_fill_timeout_s: Optional[float] = None,
                 jax_platform: Optional[str] = None):
        import jax

        if jax_platform:
            # must land before any jax array op touches a backend; an
            # env var is NOT enough — the image's sitecustomize can bake
            # its own JAX_PLATFORMS over the inherited one (same
            # override tests/conftest.py uses)
            jax.config.update("jax_platforms", jax_platform)

        from ray_tpu.models import llama

        self._llama = llama
        self.cfg, self.params = _build_model(model_size, seed)
        self.max_new_tokens = max_new_tokens
        # request clamp: each pow-2 generation-length bucket is its own
        # compiled program AND its own KV-cache footprint, so the
        # configured default is also the per-request ceiling (pass a
        # larger max_new_tokens at deploy time to allow longer asks)
        self.max_new_tokens_limit = max_new_tokens
        self._max_batch_size = max_batch_size
        # instance-level batching config consumed by @serve.batch.
        # bucket_fill_timeout_s (opt-in): once a gathering batch sits
        # at an upper pow-2 boundary, flush after this wait instead of
        # letting stragglers re-pad it into the next bucket (the
        # serialized 32+16 ragged pair that capped max_batch at 16 in
        # PERF.md's serve sweep)
        self.__serve_batch_overrides__ = {
            "_generate_batch": {
                "max_batch_size": max_batch_size,
                "bucket_fill_timeout_s": bucket_fill_timeout_s,
            },
        }

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
    async def _generate_batch(self, requests: List[dict]) -> List[List[int]]:
        """Batched generation.  Prompts are grouped by length so each
        group is one [B, T] generate call — XLA compiles per shape, and
        same-shape batches reuse the compiled prefill/decode programs.
        Each group is padded up to the next power-of-two batch size
        (repeating the first row) so only log2(max_batch)+1 shapes ever
        compile, whatever sizes the batcher hands us — shape-bucketing,
        the standard XLA serving trick (a fresh [G, T] shape is a
        multi-second compile; a bucketed one is a cache hit)."""
        import asyncio

        import jax.numpy as jnp
        import numpy as np

        def _run_groups():
            out: List[Optional[List[int]]] = [None] * len(requests)
            groups = defaultdict(list)
            for i, req in enumerate(requests):
                groups[(len(req["tokens"]), req["max_new_tokens"])].append(i)
            for (T, n_new), idxs in groups.items():
                arr = jnp.asarray(
                    [requests[i]["tokens"] for i in idxs], jnp.int32
                )
                G = arr.shape[0]
                # next pow2 >= G, but never beyond the configured batch
                # cap the replica was memory-sized for
                bucket = min(1 << (G - 1).bit_length(),
                             self._max_batch_size)
                if bucket > G:
                    arr = jnp.concatenate(
                        [arr, jnp.broadcast_to(arr[:1], (bucket - G, T))]
                    )
                # generation length is a compile axis too (the fused
                # program scans n_new steps): bucket it to the next
                # pow2 and slice, so a client sweeping max_new_tokens
                # cannot force a compile per distinct value; the KV
                # cache is (T + n) slots, so never run past max_seq_len
                # (generate() clamps per request, so this stays >= 1)
                n_bucket = max(1, min(1 << max(0, n_new - 1).bit_length(),
                                      self.cfg.max_seq_len - T))
                gen = self._llama.generate(
                    self.cfg, self.params, arr, n_bucket, temperature=0.0
                )
                # ONE device->host transfer for the whole batch.
                # Element-wise int() on the device array is a
                # per-TOKEN host read — through a remote-tunnel
                # device that is ~100 ms each, turning a 150 ms
                # generation into seconds
                gen_host = np.asarray(gen)
                for j, i in enumerate(idxs):
                    out[i] = [int(t) for t in gen_host[j, :n_new]]
            return out

        # the decode loop blocks (per-token device syncs): run it on
        # the worker pool so the replica's event loop keeps gathering
        # batches and serving health checks
        from ray_tpu.core.runtime import get_runtime

        return await asyncio.get_running_loop().run_in_executor(
            get_runtime()._exec_pool, _run_groups
        )

    async def generate(self, token_lists: List[List[int]],
                       max_new_tokens: Optional[int] = None) -> List[List[int]]:
        """Python-handle surface: a list of prompts (token ids)."""
        import asyncio

        n_new = (max_new_tokens if max_new_tokens is not None
                 else self.max_new_tokens)
        n_new = max(1, min(int(n_new), self.max_new_tokens_limit))
        # per-request validation/clamping BEFORE batching: a bad
        # request must fail alone, never take its co-batched group
        # down with it, and the clamped length must drive the grouping
        # (so n_bucket below is always >= 1)
        limit = self.cfg.max_seq_len
        reqs = []
        for toks in token_lists:
            if not toks or len(toks) >= limit:
                raise ValueError(
                    f"prompt length must be in [1, {limit - 1}] "
                    f"(got {len(toks)}; max_seq_len={limit})"
                )
            reqs.append({"tokens": toks,
                         "max_new_tokens": min(n_new, limit - len(toks))})
        return list(await asyncio.gather(*[
            self._generate_batch(r) for r in reqs
        ]))

    def bench_direct(self, batch: int, prompt_len: int,
                     max_new_tokens: int, iters: int = 3) -> dict:
        """Bare `llama.generate` baseline in the replica process (the
        chip owner); shared body with the continuous deployment."""
        return _bench_generate(self.cfg, self.params, batch,
                               prompt_len, max_new_tokens, iters)

    async def __call__(self, request):
        body = request.json() if request.body() else {}
        tokens = body["tokens"]
        n_new = int(body.get("max_new_tokens", self.max_new_tokens))
        result = await self.generate(tokens, n_new)
        return {"tokens": result}


@serve.deployment(
    max_ongoing_requests=256,
)
class ContinuousLlamaService:
    """Continuous-batching variant (reference capability: the
    vLLM-on-Ray serving pattern): requests join a RESIDENT decode
    batch mid-flight via `serve.llm_engine.LlamaEngine` instead of
    gather-batching whole generations — the decode batch stays full,
    so weight reads amortize over every active sequence.  Measured
    nearly 2x the gather-batched throughput at the same shapes
    (PERF.md round 5).  The engine's KV cache is PAGED (block pool +
    radix prefix cache), so `max_len` only caps one sequence — an
    over-provisioned pool costs HBM, not per-step time — and requests
    sharing a prompt prefix (system prompts) skip its prefill."""

    def __init__(self, model_size: str = "tiny", max_new_tokens: int = 16,
                 seed: int = 0, slots: int = 32, chunk: int = 8,
                 max_len: Optional[int] = None, block_size: int = 16,
                 kv_blocks: Optional[int] = None, prefix_cache: bool = True,
                 max_queued: Optional[int] = None,
                 decode_kernel: str = "auto", kv_dtype: str = "model",
                 weight_dtype: str = "model",
                 engine_config: Optional[dict] = None,
                 jax_platform: Optional[str] = None):
        import jax

        if jax_platform:
            jax.config.update("jax_platforms", jax_platform)

        from ray_tpu.serve.config import LLMEngineConfig
        from ray_tpu.serve.llm_engine import LlamaEngine

        if engine_config is not None:
            # declarative form (deploy documents / user_config): one
            # validated dict replaces the flat kwargs wholesale
            from ray_tpu.serve.schema import LLMEngineSchema

            ecfg = LLMEngineSchema.model_validate(engine_config).to_config()
        else:
            ecfg = LLMEngineConfig(
                slots=slots, chunk=chunk, max_len=max_len,
                block_size=block_size, kv_blocks=kv_blocks,
                prefix_cache=prefix_cache, max_queued=max_queued,
                decode_kernel=decode_kernel, kv_dtype=kv_dtype,
                weight_dtype=weight_dtype,
            ).validate()

        cfg, params = _build_model(model_size, seed)
        if ecfg.weight_dtype == "int8":
            from ray_tpu.models import llama as _llama

            params = _llama.quantize_weights_int8(params)
        # max_queued mirrors the deployment's max_queued_requests at
        # the ENGINE queue (the replica callable can't see its
        # DeploymentConfig): overflow submissions fail immediately
        # with BackPressureError -> HTTP 503 + Retry-After
        self.engine = LlamaEngine(cfg, params, **ecfg.engine_kwargs())
        self.max_new_tokens = max_new_tokens
        self.max_new_tokens_limit = max_new_tokens

    async def generate(self, token_lists, max_new_tokens=None):
        import asyncio

        from ray_tpu.core.runtime import remaining_deadline_s

        n_new = (max_new_tokens if max_new_tokens is not None
                 else self.max_new_tokens)
        n_new = max(1, min(int(n_new), self.max_new_tokens_limit))
        # the caller's end-to-end budget (handle.options(timeout_s=...)
        # propagated into this task gRPC-style) rides into the engine
        # queue, so a request that cannot decode its first token before
        # the caller gives up is SHED before it burns a prefill
        budget = remaining_deadline_s()
        futs = [
            asyncio.wrap_future(
                self.engine.submit(list(t), n_new, timeout_s=budget)
            )
            for t in token_lists
        ]
        return list(await asyncio.gather(*futs))

    async def __call__(self, request):
        body = request.json() if request.body() else {}
        n_new = int(body.get("max_new_tokens", self.max_new_tokens))
        return {"tokens": await self.generate(body["tokens"], n_new)}

    def stats(self):
        """Queue-depth/TTFT/occupancy signals, piggybacked by the serve
        replica onto health checks: the controller feeds `queue_depth`
        into routing tables (queue-depth-aware pow-2 across replicas)
        and the rest into /api/serve."""
        return self.engine.stats()

    def bench_direct(self, batch: int, prompt_len: int,
                     max_new_tokens: int, iters: int = 3) -> dict:
        """Bare gather-generate baseline in the engine's process (the
        engine idles between requests, so the chip is free); shared
        body with LlamaService."""
        return _bench_generate(self.engine.cfg, self.engine.params,
                               batch, prompt_len, max_new_tokens, iters)

    def __serve_drain__(self):
        """Graceful scale-down hook (called by the replica once the
        controller has removed it from routing tables): stop admitting
        new requests while live sequences decode to completion."""
        self.engine.begin_drain()

    def __serve_shutdown__(self):
        """Post-drain hook: release the KV block pool deterministically
        instead of relying on actor-kill teardown."""
        self.engine.shutdown()

    def __del__(self):
        try:
            self.engine.shutdown()
        except Exception:
            pass


def build_app(model_size: str = "tiny", max_new_tokens: int = 16):
    return LlamaService.bind(model_size=model_size,
                             max_new_tokens=max_new_tokens)


def run(model_size: str = "tiny", max_new_tokens: int = 16,
        name: str = "llm", route_prefix: str = "/llm",
        timeout_s: float = 300.0):
    """Deploy and return the app handle.  The ready timeout covers a
    cold replica init on real chips (first jax/TPU init in a fresh
    worker is tens of seconds; big-model weight init longer)."""
    return serve.run(
        build_app(model_size, max_new_tokens),
        name=name, route_prefix=route_prefix, timeout_s=timeout_s,
    )
