"""BASELINE config #3 parity demo: PPO on pixels with the new-stack
Learner API.

Reference: "RLlib PPO Atari Breakout (new Learner API, 4 learner
workers)" — ALE isn't installable in this image, so the procedural
pixel env (`CatchPixelEnv`) stands in: (H, W, C) image observations
through the CNN encoder, the same stack an Atari run uses
(`wrap_atari_connectors` supplies the warp/stack pipeline for real
gymnasium image envs).

Run: `python -m ray_tpu.examples.ppo_pixels` (inside `rt.init`), or
call `run()` from tests.
"""

from __future__ import annotations

from typing import Dict


def run(iterations: int = 45, *, num_env_runners: int = 1,
        num_learners: int = 0, target_return: float = 0.6,
        seed: int = 0) -> Dict[str, float]:
    """Train PPO+CNN on the pixel env until it catches reliably;
    returns the final metrics (episode_return_mean ~1.0 = perfect)."""
    import numpy as np

    from ray_tpu.rllib import CNNModule, PPOConfig

    cfg = (PPOConfig()
           .environment("Catch-v0")
           .env_runners(num_env_runners=num_env_runners,
                        num_envs_per_env_runner=16,
                        rollout_fragment_length=32)
           .training(lr=1e-3, minibatch_size=256, num_epochs=4,
                     model={"conv_filters": ((16, 3, 2), (32, 3, 2)),
                            "hidden": (128,)})
           .learners(num_learners=num_learners)
           .debugging(seed=seed))
    algo = cfg.build()
    try:
        assert isinstance(algo.module, CNNModule)  # pixel path engaged
        best = -1.0
        result: Dict[str, float] = {}
        for _ in range(iterations):
            result = algo.train()
            ret = result.get("episode_return_mean")
            if ret is not None and np.isfinite(ret):
                best = max(best, float(ret))
            if best >= target_return:
                break
        result["best_return"] = best
        return result
    finally:
        algo.stop()


if __name__ == "__main__":
    import json

    import ray_tpu as rt

    rt.init(num_workers=2, num_cpus=8, ignore_reinit_error=True)
    try:
        out = run()
        print(json.dumps({k: v for k, v in out.items()
                          if isinstance(v, (int, float))}, indent=2))
    finally:
        rt.shutdown()
