"""Runnable examples doubling as integration references (reference:
`train/examples/`, `release/air_tests/air_benchmarks/`)."""
