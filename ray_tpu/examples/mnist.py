"""Fashion-MNIST-shaped distributed training example.

Reference: BASELINE config #1 — `ray.train.torch.TorchTrainer` MNIST
fashion (2 CPU workers, DDP) — re-expressed as a JaxTrainer
data-parallel run: each worker trains the same jax MLP on its data
shard and gradients mean-allreduce across the worker group every step.

The dataset is a deterministic synthetic stand-in with Fashion-MNIST's
shape (784 features, 10 classes): a fixed random teacher network labels
random inputs, so accuracy is a real learnability signal without
downloading data (this image has zero egress).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu import train
from ray_tpu.train import JaxConfig, JaxTrainer, RunConfig, ScalingConfig


def make_dataset(n: int = 4096, seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """[n, 784] float32 features, [n] int labels from a fixed teacher."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 784)).astype(np.float32)
    w1 = np.random.default_rng(1234).normal(size=(784, 32)).astype(np.float32)
    w2 = np.random.default_rng(5678).normal(size=(32, 10)).astype(np.float32)
    y = np.argmax(np.tanh(x @ w1) @ w2, axis=1).astype(np.int32)
    return x, y


def train_func(config: Dict[str, Any]):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.train import jax_utils

    ctx = train.get_context()
    rank, world = ctx.get_world_rank(), ctx.get_world_size()
    epochs = config.get("epochs", 4)
    batch_size = config.get("batch_size", 128)
    hidden = config.get("hidden", 128)
    lr = config.get("lr", 1e-3)

    x, y = make_dataset(config.get("n", 4096))
    # contiguous per-rank shard (reference: DistributedSampler)
    shard = slice(rank * len(x) // world, (rank + 1) * len(x) // world)
    x, y = x[shard], y[shard]

    def init(key):
        k1, k2 = jax.random.split(key)
        return {
            "w1": jax.random.normal(k1, (784, hidden), jnp.float32) * 0.05,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, 10), jnp.float32) * 0.05,
            "b2": jnp.zeros((10,)),
        }

    def logits_fn(p, xb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def loss_fn(p, xb, yb):
        logp = jax.nn.log_softmax(logits_fn(p, xb))
        return -jnp.mean(jnp.take_along_axis(logp, yb[:, None], axis=1))

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    opt = optax.adam(lr)
    params = init(jax.random.PRNGKey(0))  # same seed: replicas identical
    opt_state = opt.init(params)

    steps = max(1, len(x) // batch_size)
    for epoch in range(epochs):
        perm = np.random.default_rng(epoch).permutation(len(x))
        total_loss = 0.0
        for s in range(steps):
            idx = perm[s * batch_size:(s + 1) * batch_size]
            loss, grads = grad_fn(params, x[idx], y[idx])
            # DDP step: host-level mean-allreduce across workers
            grads = jax_utils.sync_gradients(grads)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            total_loss += float(loss)
        preds = np.asarray(jax.jit(logits_fn)(params, x)).argmax(axis=1)
        acc = jax_utils.world_mean(float((preds == y).mean()))
        train.report({
            "loss": total_loss / steps,
            "accuracy": acc,
            "epoch": epoch,
        })


def run(num_workers: int = 2, epochs: int = 4, storage_path: Optional[str] = None):
    trainer = JaxTrainer(
        train_func,
        train_loop_config={"epochs": epochs},
        scaling_config=ScalingConfig(num_workers=num_workers),
        jax_config=JaxConfig(distributed_mode="collective", platform="cpu"),
        run_config=RunConfig(name="mnist_fashion", storage_path=storage_path),
    )
    return trainer.fit()


if __name__ == "__main__":
    import ray_tpu as rt

    rt.init(num_workers=3, num_cpus=8, ignore_reinit_error=True)
    result = run()
    print("final:", result.metrics)
    rt.shutdown()
