"""Runtime-plane microbenchmarks (reference capability:
`python/ray/_private/ray_perf.py` — `ray microbenchmark` — and
`release/benchmarks/`; numbers table in BASELINE.md).

Measures the task/actor/object-plane hot paths end-to-end against a
real local cluster:

    python -m ray_tpu.scripts.perf [--filter pat] [--json out.json]
           [--rounds N] [--round-sec S]

Each benchmark reports ops/s (mean ± sd over rounds).  The matrix
mirrors the reference's microbenchmark names so BASELINE.md rows are
directly comparable (hardware caveats apply — record machine specs
next to any saved run).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def timeit(name: str, fn: Callable[[], None], multiplier: float = 1.0,
           rounds: int = 3, round_sec: float = 1.0,
           warmup_sec: float = 0.5) -> Tuple[str, float, float]:
    """Run `fn` repeatedly; returns (name, ops/s mean, sd)."""
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < warmup_sec:
        fn()
        count += 1
    step = max(1, count // 5)
    stats = []
    for _ in range(rounds):
        start = time.perf_counter()
        count = 0
        while time.perf_counter() - start < round_sec:
            for _ in range(step):
                fn()
            count += step
        stats.append(multiplier * count / (time.perf_counter() - start))
    mean = statistics.fmean(stats)
    sd = statistics.stdev(stats) if len(stats) > 1 else 0.0
    print(f"{name}: {mean:,.2f} +- {sd:,.2f} per second", flush=True)
    return (name, mean, sd)


# ---------------------------------------------------------------------
# benchmark bodies (module-level so tasks pickle by reference)
# ---------------------------------------------------------------------
def _small_value():
    return 0


def _put_small_batch(rt_mod, n=100):
    import ray_tpu as rt

    for _ in range(n):
        rt.put(0)
    return 0


class _PerfActor:
    def small_value(self):
        return 0

    def small_value_batch(self, n):
        return [0] * n

    def submit_task_batch(self, n):
        """Acts as an independent client: submits n tasks of its own
        (the reference's multi-client benchmark shape)."""
        import ray_tpu as rt

        fn = rt.remote(num_cpus=0)(_small_value)
        return len(rt.get([fn.remote() for _ in range(n)]))


class _AsyncPerfActor:
    async def small_value(self):
        return 0


def build_matrix(rt, args):
    """(name, factory, ops-multiplier) triples.  Each factory returns
    (body, cleanup); actors are created lazily inside the factory and
    killed by cleanup so earlier rows aren't polluted by the background
    load of processes later rows need (matters on small hosts)."""
    small_value = rt.remote(num_cpus=0)(_small_value)
    put_batch = rt.remote(num_cpus=0)(_put_small_batch)
    Actor = rt.remote(num_cpus=0)(_PerfActor)
    AsyncActor = rt.remote(num_cpus=0)(_AsyncPerfActor)
    _none = lambda: None  # noqa: E731

    def get_small_f():
        value_ref = rt.put(0)
        return (lambda: rt.get(value_ref)), _none

    def put_small_f():
        return (lambda: rt.put(0)), _none

    def put_large_f():
        arr = np.zeros(100 * 1024 * 1024 // 8, dtype=np.int64)  # 100 MB
        return (lambda: rt.put(arr)), _none

    def multi_client_put_f():
        body = lambda: rt.get(  # noqa: E731
            [put_batch.remote(None) for _ in range(4)]
        )
        return body, _none

    def task_sync_f():
        return (lambda: rt.get(small_value.remote())), _none

    def tasks_async_f():
        body = lambda: rt.get(  # noqa: E731
            [small_value.remote() for _ in range(1000)]
        )
        return body, _none

    def multi_client_tasks_f():
        # each actor is an independent client submitting its own tasks
        actors = [Actor.remote() for _ in range(4)]
        rt.get([a.small_value.remote() for a in actors])
        body = lambda: rt.get(  # noqa: E731
            [a.submit_task_batch.remote(250) for a in actors]
        )
        return body, lambda: [rt.kill(a) for a in actors]

    def actor_sync_f():
        a = Actor.remote()
        rt.get(a.small_value.remote())
        return (lambda: rt.get(a.small_value.remote())), lambda: rt.kill(a)

    def actor_async_f():
        a = Actor.remote()
        rt.get(a.small_value.remote())
        body = lambda: rt.get(  # noqa: E731
            [a.small_value.remote() for _ in range(1000)]
        )
        return body, lambda: rt.kill(a)

    def async_actor_f():
        a = AsyncActor.remote()
        rt.get(a.small_value.remote())
        body = lambda: rt.get(  # noqa: E731
            [a.small_value.remote() for _ in range(1000)]
        )
        return body, lambda: rt.kill(a)

    def n_n_actors_f():
        actors = [Actor.remote() for _ in range(4)]
        rt.get([a.small_value.remote() for a in actors])

        def body():
            refs = []
            for a in actors:
                refs.extend(a.small_value.remote() for _ in range(250))
            rt.get(refs)

        return body, lambda: [rt.kill(a) for a in actors]

    def wait_1k_f():
        def body():
            not_ready = [small_value.remote() for _ in range(1000)]
            while not_ready:
                _ready, not_ready = rt.wait(not_ready)

        return body, _none

    def pg_f():
        from ray_tpu.util import placement_group, remove_placement_group

        def body():
            pg = placement_group([{"CPU": 0.01}])
            pg.ready(timeout=10)
            remove_placement_group(pg)

        return body, _none

    return [
        ("single client get calls (shm store)", get_small_f, 1),
        ("single client put calls (shm store)", put_small_f, 1),
        ("single client put gigabytes", put_large_f, 0.1),
        ("multi client put calls (shm store)", multi_client_put_f, 400),
        ("single client tasks sync", task_sync_f, 1),
        ("single client tasks async", tasks_async_f, 1000),
        ("multi client tasks async", multi_client_tasks_f, 1000),
        ("1:1 actor calls sync", actor_sync_f, 1),
        ("1:1 actor calls async", actor_async_f, 1000),
        ("1:1 async-actor calls async", async_actor_f, 1000),
        ("n:n actor calls async", n_n_actors_f, 1000),
        ("single client wait 1k refs", wait_1k_f, 1),
        ("placement group create/removal", pg_f, 1),
    ]


def _shard_snapshot() -> List[Dict]:
    from ray_tpu.core.runtime import get_runtime

    return get_runtime().owner_shard_stats()


def owner_shard_report(before: List[Dict], after: List[Dict]) -> List[Dict]:
    """Per-shard delta rows for one measured run: tasks completed on
    each shard and the shard thread's CPU us per task — the accounting
    that proves shard scaling is flat even when the host lacks the
    cores to show a wall-clock win (PERF.md cost model)."""
    rows = []
    for b, a in zip(before, after):
        done = a["completed"] - b["completed"]
        cpu = a["cpu_s"] - b["cpu_s"]
        rows.append({
            "shard": a["shard"],
            "submitted": a["submitted"] - b["submitted"],
            "completed": done,
            "cpu_s": round(cpu, 3),
            "us_per_task": round(cpu * 1e6 / done, 1) if done else 0.0,
        })
    return rows


def measure_task_storm(rt, n: int = 1000) -> Dict[str, float]:
    """Submit `n` no-op tasks at once and track each completion time —
    the per-task latency distribution under a full queue bounds the
    runtime's scheduling throughput at depth (VERDICT r2: the 1-vCPU
    microbench rows leave it unmeasured; reference analog: the
    1M-tasks-queued single-node scalability case)."""
    import time as _t

    @rt.remote
    def _noop():
        return 0

    rt.get(_noop.remote())  # warm a lease
    t0 = _t.perf_counter()
    refs = [_noop.remote() for _ in range(n)]
    submit_s = _t.perf_counter() - t0
    lat: List[float] = []
    pending = refs
    while pending:
        done, pending = rt.wait(pending, num_returns=1)
        lat.append(_t.perf_counter() - t0)
        for d in done:
            rt.get(d)
    lat_arr = np.asarray(lat)
    return {
        "submit_s": submit_s,
        "drain_s": float(lat_arr[-1]),
        "p50_s": float(np.percentile(lat_arr, 50)),
        "p95_s": float(np.percentile(lat_arr, 95)),
        "p100_s": float(lat_arr.max()),
        "tasks_per_s": n / float(lat_arr.max()),
    }


# ----------------------------------------------------------------------
# control-plane core scaling (VERDICT r3 #4: the asyncio-control-plane
# bet is validated per-core only — measure where CPU time goes and what
# dedicated cores buy)
# ----------------------------------------------------------------------
def _proc_tree_cpu() -> Dict[int, Dict[str, object]]:
    """pid -> {ppid, role, ticks} for this process and its descendants
    (driver, node daemon, workers), from /proc — no psutil dependency."""
    procs: Dict[int, Dict[str, object]] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        pid = int(entry)
        try:
            with open(f"/proc/{pid}/stat") as f:
                stat = f.read()
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read().replace(b"\0", b" ").decode(
                    errors="replace")
        except OSError:
            continue
        # comm may contain spaces/parens: split after the LAST ')'
        rest = stat.rsplit(")", 1)[1].split()
        ppid = int(rest[1])      # field 4
        utime = int(rest[11])    # field 14
        stime = int(rest[12])    # field 15
        procs[pid] = {"ppid": ppid, "cmdline": cmdline,
                      "ticks": utime + stime}
    me = os.getpid()
    keep: Dict[int, Dict[str, object]] = {}
    children: Dict[int, List[int]] = {}
    for pid, info in procs.items():
        children.setdefault(info["ppid"], []).append(pid)
    stack = [me]
    while stack:
        pid = stack.pop()
        if pid not in procs:
            continue
        keep[pid] = procs[pid]
        stack.extend(children.get(pid, []))
    for pid, info in keep.items():
        cmd = info["cmdline"]
        if pid == me:
            info["role"] = "driver"
        elif "noded" in cmd:
            info["role"] = "noded"
        elif "worker_main" in cmd:
            info["role"] = "worker"
        else:
            info["role"] = "other"
    return keep


def measure_core_split(rt, n: int = 1000) -> Dict[str, float]:
    """Task-storm with per-component CPU accounting: how many CPU
    microseconds each plane (driver runtime, node daemon, workers)
    burns per task.  On a 1-core box throughput ~= 1e6 / SUM(us); with
    each plane on its own core the pipeline bound is 1e6 / MAX(us) —
    the analytic multi-core projection PERF.md records.  On multi-core
    rigs combine with --pin-cores for the measured curve."""
    # warm-up storm: spawn/prestart every worker BEFORE the snapshot,
    # or their multi-second import cost pollutes the per-task delta
    measure_task_storm(rt, n=min(200, n))
    before = _proc_tree_cpu()
    dist = measure_task_storm(rt, n=n)
    after = _proc_tree_cpu()
    tick = os.sysconf("SC_CLK_TCK")
    split_us = {r: 0.0 for r in ("driver", "noded", "worker", "other")}
    steady_workers = 0
    for pid, info in after.items():
        prev = before.get(pid)
        if prev is None:
            continue  # spawned mid-storm: startup cost, not task cost
        if info["role"] == "worker":
            steady_workers += 1
        delta = (info["ticks"] - prev["ticks"]) / tick
        split_us[info["role"]] += delta * 1e6 / n
    total_us = sum(v for v in split_us.values() if v > 0)
    # the worker plane is a POOL: its cost spreads over num_workers
    # cores; driver and daemon are single event loops (one core each).
    # Only workers present for the WHOLE storm count — their CPU is
    # what the deltas above summed.
    n_workers = max(1, steady_workers)
    plane_us = {
        "driver": split_us["driver"],
        "noded": split_us["noded"],
        "worker_pool": split_us["worker"] / n_workers,
    }
    bottleneck = max(plane_us, key=plane_us.get)
    # every delta can round to zero ticks on tiny storms
    # (SC_CLK_TCK=100 -> 10 ms granularity): report, don't divide
    projected = (
        round(1e6 / plane_us[bottleneck], 1)
        if plane_us[bottleneck] > 0 else 0.0
    )
    return {
        **{f"{k}_us_per_task": round(v, 1) for k, v in split_us.items()},
        "num_workers": float(n_workers),
        "total_us_per_task": round(total_us, 1),
        "measured_tasks_per_s": round(dist["tasks_per_s"], 1),
        "projected_pipelined_tasks_per_s": projected,
        "bottleneck": bottleneck,
    }


def apply_core_pinning(cores: int) -> Dict[str, List[int]]:
    """Pin each plane to its own core(s): driver -> 0, node daemon ->
    1, workers round-robin over the rest (reference analog: the
    release-test rigs isolate raylet/worker CPU).  Requires a box with
    >= `cores` cores; returns the placement actually applied.

    Pinning covers processes alive NOW: workers respawned later
    inherit the daemon's single-core affinity — warm the worker pool
    first (main() runs a warm-up storm before pinning) and re-apply
    after any worker churn."""
    avail = sorted(os.sched_getaffinity(0))
    if len(avail) < cores:
        raise RuntimeError(
            f"--pin-cores {cores} needs {cores} cores; this box exposes "
            f"{len(avail)} ({avail})"
        )
    use = avail[:cores]
    placement: Dict[str, List[int]] = {}
    for pid, info in _proc_tree_cpu().items():
        role = info["role"]
        if role == "driver":
            core = use[0]
        elif role == "noded":
            core = use[1 % len(use)]
        else:  # workers + other spread over the remaining cores
            rest = use[2:] or use
            core = rest[pid % len(rest)]
        try:
            os.sched_setaffinity(pid, {core})
            placement.setdefault(role, []).append(core)
        except OSError:
            pass
    return placement


class _BusbwMember:
    def __init__(self, rank, world, size_mb):
        from ray_tpu.parallel import collectives as col

        self.g = col.init_collective_group(world, rank,
                                           group_name="perf_busbw")
        self.world = world
        self.arr = np.random.default_rng(rank).standard_normal(
            size_mb * 1024 * 1024 // 8
        )

    def run(self, iters):
        import time as _t

        self.g.barrier()
        t0 = _t.perf_counter()
        for _ in range(iters):
            self.g.allreduce(self.arr)
        dt = _t.perf_counter() - t0
        # ring algorithm bus bandwidth convention (NCCL tests):
        # busbw = 2*(n-1)/n * size / time
        n = self.world
        return (2 * (n - 1) / n) * self.arr.nbytes * iters / dt / 1e9


def measure_allreduce_busbw(rt, world: int = 2, size_mb: int = 16,
                            iters: int = 3) -> float:
    """Host-tier ring-allreduce bus bandwidth in GB/s (the BASELINE
    north-star metric the reference measures with nccl-tests against
    `util.collective`)."""
    Member = rt.remote(num_cpus=0)(_BusbwMember)
    members = [Member.remote(i, world, size_mb) for i in range(world)]
    vals = rt.get([m.run.remote(iters) for m in members], timeout=600)
    for m in members:
        rt.kill(m)
    try:  # the named rendezvous must not survive into a rerun
        rt.kill(rt.get_actor("__rt_collective__perf_busbw"))
    except Exception:
        pass
    return float(min(vals))


# ----------------------------------------------------------------------
# scalability envelope (reference:
# `release/benchmarks/single_node/test_single_node.py:12-53` and
# `release/benchmarks/object_store/test_object_store.py` — the published
# envelope BASELINE.md carries: 10k args to one task, 3k returns,
# 10k-ref get, 1M queued tasks, 100 GiB objects, 1 GiB broadcast)
# ----------------------------------------------------------------------
def _count_args(*args):
    return len(args)


def _envelope_checksum(arr):
    return int(arr[0]), int(arr[-1]), int(arr.nbytes)


def _rss_gb(pid: int = 0) -> float:
    try:
        with open(f"/proc/{pid or os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024 / 1024
    except OSError:
        pass
    return 0.0


def _warm_sleep(sec):
    time.sleep(sec)
    return 0


def measure_envelope(rt, *, args_n: int = 10_000, returns_n: int = 3_000,
                     get_n: int = 10_000, queue_n: int = 100_000,
                     large_gb: float = 50.0, num_workers: int = 4,
                     rows: Optional[List[str]] = None) -> Dict[str, Dict]:
    """Single-node envelope rows (the broadcast row needs a multi-node
    cluster — `measure_envelope_broadcast`).  Each row returns measured
    seconds; a row that raises records the failure instead of killing
    the run, so one cliff doesn't hide the others."""
    rows = rows or ["args", "returns", "get", "queue", "large"]
    out: Dict[str, Dict] = {}

    def _row(name, fn):
        if name not in rows:
            return
        try:
            out[name] = fn()
            print(f"envelope[{name}]: " + ", ".join(
                f"{k}={v}" for k, v in out[name].items()), flush=True)
        except Exception as e:  # record the cliff, keep going
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"envelope[{name}] FAILED: {e}", flush=True)

    count_args = rt.remote(num_cpus=0)(_count_args)
    # boot the whole worker pool before timing anything: a cold worker
    # pays seconds of interpreter+jax import, which is boot latency,
    # not envelope capacity.  The sleeps overlap, so the tasks cannot
    # all pipeline onto the first worker to register — every pool slot
    # must boot to drain this batch
    warm = rt.remote(num_cpus=1)(_warm_sleep)
    rt.get([warm.remote(0.5) for _ in range(2 * num_workers)])

    def row_args():
        t0 = time.perf_counter()
        refs = [rt.put(0) for _ in range(args_n)]
        put_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        got = rt.get(count_args.remote(*refs))
        call_s = time.perf_counter() - t0
        assert got == args_n, got
        return {"n": args_n, "put_s": round(put_s, 2),
                "call_s": round(call_s, 2),
                "total_s": round(put_s + call_s, 2)}

    def row_returns():
        many = rt.remote(num_cpus=0, num_returns=returns_n)(
            lambda: tuple(range(returns_n))
        )
        t0 = time.perf_counter()
        refs = many.remote()
        vals = rt.get(list(refs))
        dt = time.perf_counter() - t0
        assert vals[0] == 0 and vals[-1] == returns_n - 1
        return {"n": returns_n, "total_s": round(dt, 2)}

    def row_get():
        refs = [rt.put(i) for i in range(get_n)]
        t0 = time.perf_counter()
        vals = rt.get(refs)
        dt = time.perf_counter() - t0
        assert vals[-1] == get_n - 1
        return {"n": get_n, "get_s": round(dt, 2)}

    def row_queue():
        noop = rt.remote(num_cpus=0.001)(_small_value)
        shards_before = _shard_snapshot()
        t0 = time.perf_counter()
        refs = [noop.remote() for _ in range(queue_n)]
        submit_s = time.perf_counter() - t0
        rss_peak = _rss_gb()
        t0 = time.perf_counter()
        step = 10_000
        for i in range(0, queue_n, step):
            rt.get(refs[i:i + step])
        drain_s = time.perf_counter() - t0
        out = {"n": queue_n, "submit_s": round(submit_s, 2),
               "submit_per_s": round(queue_n / submit_s, 1),
               "drain_s": round(drain_s, 2),
               "tasks_per_s": round(queue_n / (submit_s + drain_s), 1),
               "driver_rss_gb": round(rss_peak, 2)}
        shard_rows = owner_shard_report(shards_before, _shard_snapshot())
        if len(shard_rows) > 1 or shard_rows[0]["completed"]:
            out["owner_shards"] = shard_rows
        return out

    def row_large():
        n = int(large_gb * (1 << 30))
        # zeros: source pages stay the kernel zero page until written,
        # so the numpy side costs ~nothing — the shm copy is the cost
        arr = np.zeros(n, dtype=np.uint8)
        arr[0], arr[-1] = 7, 9  # corners prove round-trip integrity
        t0 = time.perf_counter()
        ref = rt.put(arr)
        put_s = time.perf_counter() - t0
        del arr
        t0 = time.perf_counter()
        got = rt.get(ref)
        get_s = time.perf_counter() - t0
        assert got[0] == 7 and got[-1] == 9 and got.nbytes == n
        del got, ref
        return {"gib": large_gb, "put_s": round(put_s, 2),
                "get_s": round(get_s, 2),
                "put_gb_per_s": round(large_gb / put_s, 2),
                "get_gb_per_s": round(large_gb / max(get_s, 1e-9), 2)}

    _row("args", row_args)
    _row("returns", row_returns)
    _row("get", row_get)
    _row("queue", row_queue)
    _row("large", row_large)
    return out


def measure_envelope_broadcast(n_nodes: int = 4, size_gb: float = 1.0,
                               workers_per_node: int = 1) -> Dict[str, float]:
    """1 GiB object broadcast to every node of a local multi-node
    cluster (reference: `object_store.json` 1 GiB x 50 nodes over the
    network; here the nodes share a host, so this measures the chunked
    daemon-to-daemon transfer path, fan-out dedup included).  Owns its
    cluster: call with no runtime initialized."""
    import ray_tpu as rt_mod
    from ray_tpu.cluster_utils import Cluster

    if rt_mod.is_initialized():
        raise RuntimeError(
            "envelope broadcast owns its cluster: call with no "
            "runtime initialized"
        )
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 2, "num_workers": 1})
    c.connect()
    try:
        for i in range(n_nodes):
            c.add_node(num_cpus=2, resources={f"bn{i}": 1},
                       num_workers=workers_per_node)
        c.wait_for_nodes()
        checksum = rt_mod.remote(num_cpus=0)(_envelope_checksum)
        n = int(size_gb * (1 << 30))
        arr = np.zeros(n, dtype=np.uint8)
        arr[0], arr[-1] = 3, 5
        ref = rt_mod.put(arr)
        del arr
        t0 = time.perf_counter()
        outs = rt_mod.get([
            checksum.options(resources={f"bn{i}": 1}).remote(ref)
            for i in range(n_nodes)
        ])
        dt = time.perf_counter() - t0
        assert all(o == (3, 5, n) for o in outs), outs
        return {"nodes": n_nodes, "gib": size_gb,
                "broadcast_s": round(dt, 2),
                "aggregate_gb_per_s": round(n_nodes * size_gb / dt, 2)}
    finally:
        c.shutdown()


# ----------------------------------------------------------------------
# serve LLM engine: paged-KV tick trace + CB smoke (CPU tiny model)
# ----------------------------------------------------------------------
def _engine_run(eng, prompts, n_new: int) -> Dict[str, float]:
    """Drive one engine through a closed workload; returns tok/s plus
    the engine's per-tick counters — as DELTAS over the engine's state
    at entry, so a warm-up run's work never inflates a measured row."""
    base = eng.stats()
    futs = [eng.submit(p, n_new) for p in prompts]
    t0 = time.perf_counter()
    for f in futs:
        f.result(timeout=600)
    wall = time.perf_counter() - t0
    s = eng.stats()
    hit = s["prefix_hit_tokens"] - base["prefix_hit_tokens"]
    filled = s["prefill_tokens"] - base["prefill_tokens"]
    return {
        "tokens_per_sec": round(len(prompts) * n_new / wall, 1),
        "wall_s": round(wall, 3),
        "ticks": s["ticks"] - base["ticks"],
        "tick_ema_ms": round(s["tick_ema_s"] * 1e3, 2),
        "gather_blocks": s["gather_blocks"],
        "prefill_calls": s["prefill_calls"] - base["prefill_calls"],
        "prefill_tokens": filled,
        "prefix_hit_tokens": hit,
        "prefix_hit_rate": round(
            hit / (hit + filled) if hit + filled else 0.0, 3
        ),
        "ttft_ema_ms": round(s["ttft_ema_s"] * 1e3, 1),
    }


def measure_engine_trace(*, requests: int = 24, n_new: int = 8,
                         seed: int = 0) -> Dict[str, Dict[str, float]]:
    """Paged-KV acceptance rows on the CPU tiny model (the per-chip
    claims, measured without the serve stack in the way):

    - `sized` vs `overprovisioned`: the same workload on a
      workload-sized KV budget vs a ~1024-token budget.  With the old
      per-slot ring, over-provisioning was a ~20x per-step tax
      (PERF.md); with paged blocks the gather width tracks LIVE tokens,
      so the two rows must run the same compiled programs (equal
      `gather_blocks`) at near-equal throughput.
    - `prefix_on` vs `prefix_off`: a shared-system-prompt workload with
      the radix cache on/off — cached requests skip the shared
      prefill, visible as fewer prefilled tokens and a lower TTFT.
    - `serve_llm_cb_smoke`: the continuous-batching hot path's tok/s —
      the tier-1 regression canary (`tests/test_perf_harness.py`).
    """
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm_engine import LlamaEngine

    cfg = llama.LlamaConfig.tiny(vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict[str, float]] = {}

    # -- pool-budget invariance (prompt 24 + 8 new = 32 live tokens) --
    bs = 8  # engine block_size for every row below
    prompts = [
        [int(x) for x in rng.integers(1, cfg.vocab_size, size=24)]
        for _ in range(requests)
    ]
    for name, kw in (
        ("sized", dict(max_len=48, kv_blocks=4 * 48 // bs)),
        ("overprovisioned", dict(max_len=120, kv_blocks=1024 // bs)),
    ):
        eng = LlamaEngine(cfg, params, slots=4, chunk=4, block_size=bs,
                          prefix_cache=False, **kw)
        try:
            _engine_run(eng, prompts[:4], n_new)  # warm compiles
            out[name] = _engine_run(eng, prompts, n_new)
            out[name]["kv_budget_tokens"] = kw["kv_blocks"] * bs
        finally:
            eng.shutdown()
        print(f"engine[{name}]: " + ", ".join(
            f"{k}={v}" for k, v in out[name].items()), flush=True)

    # -- radix prefix reuse (shared 16-token system prompt) -----------
    system = [int(x) for x in rng.integers(1, cfg.vocab_size, size=16)]
    shared_prompts = [
        system + [int(x) for x in rng.integers(1, cfg.vocab_size, size=6)]
        for _ in range(requests)
    ]
    for name, pc in (("prefix_on", True), ("prefix_off", False)):
        eng = LlamaEngine(cfg, params, slots=4, chunk=4, block_size=bs,
                          max_len=48, prefix_cache=pc)
        try:
            _engine_run(eng, shared_prompts[:2], n_new)  # warm compiles
            out[name] = _engine_run(eng, shared_prompts, n_new)
        finally:
            eng.shutdown()
        print(f"engine[{name}]: " + ", ".join(
            f"{k}={v}" for k, v in out[name].items()), flush=True)

    # -- CB smoke: the default-config hot path, one number ------------
    eng = LlamaEngine(cfg, params, slots=4, chunk=4, block_size=bs,
                      max_len=48)
    try:
        # warm both prefill paths: the repeated prompt takes the radix
        # suffix-prefill route, so its compile stays out of the timing
        _engine_run(eng, prompts[:4] + prompts[:1], n_new)
        out["serve_llm_cb_smoke"] = _engine_run(eng, prompts, n_new)
    finally:
        eng.shutdown()
    print("engine[serve_llm_cb_smoke]: " + ", ".join(
        f"{k}={v}" for k, v in out["serve_llm_cb_smoke"].items()),
        flush=True)
    return out


def measure_decode_kernel(*, batches=(16, 32, 64), n_new: int = 8,
                          seed: int = 0) -> Dict[str, Dict[str, float]]:
    """Bare-decode rows for the fused paged-attention kernel
    (`ops/paged_attention.py`) vs the gather+`decode_step_vec`
    reference route, plus the int8 pool-occupancy row.

    - `decode_b{B}_{pallas,gather}`: the same short-prompt workload at
      batch B through each decode route; the dispatch counters prove
      which plane actually ran (kernel rows must show zero fallback
      ticks and `gather_blocks == 0` growth on the decode hot loop).
    - `kv_pool_occupancy`: payload bytes of an int8 pool vs the bf16
      pool at the SAME block budget — the int8 row must sit at half,
      with the f32 scale sidecar priced separately.

    Off-TPU the kernel runs in Pallas interpret mode, so CPU tok/s
    compares an interpreter against compiled XLA — the rows are
    structural evidence (kernel dispatched, gather plane dead), not a
    speed claim.  On TPU the same rows are the perf claim.
    """
    import jax

    from ray_tpu.models import llama
    from ray_tpu.serve.llm_engine import LlamaEngine

    cfg = llama.LlamaConfig.tiny(vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    out: Dict[str, Dict[str, float]] = {}
    bs = 8   # engine block_size
    plen = 8  # short prompts: decode ticks dominate the trace
    for b in batches:
        prompts = [
            [int(x) for x in rng.integers(1, cfg.vocab_size, size=plen)]
            for _ in range(b)
        ]
        for mode in ("pallas", "gather"):
            eng = LlamaEngine(cfg, params, slots=b, chunk=4,
                              block_size=bs, max_len=plen + n_new + 2,
                              prefix_cache=False, decode_kernel=mode)
            name = f"decode_b{b}_{mode}"
            try:
                _engine_run(eng, prompts[: max(1, b // 4)], n_new)
                out[name] = _engine_run(eng, prompts, n_new)
                s = eng.stats()
                out[name]["decode_kernel"] = s["decode_kernel"]
                out[name]["kernel_ticks"] = (
                    s["decode_kernel_dispatch_total"])
                out[name]["fallback_ticks"] = (
                    s["decode_fallback_dispatch_total"])
            finally:
                eng.shutdown()
            print(f"decode[{name}]: " + ", ".join(
                f"{k}={v}" for k, v in out[name].items()), flush=True)

    # -- int8 vs bf16 pool occupancy at equal block budget ------------
    occ: Dict[str, float] = {}
    for name, kvd in (("fp", "model"), ("int8", "int8")):
        eng = LlamaEngine(cfg, params, slots=4, chunk=4, block_size=bs,
                          max_len=plen + n_new + 2, kv_blocks=64,
                          prefix_cache=False, kv_dtype=kvd)
        try:
            s = eng.stats()
            occ[f"kv_pool_bytes_{name}"] = s["kv_pool_bytes"]
            occ[f"kv_scale_bytes_{name}"] = s["kv_scale_bytes"]
        finally:
            eng.shutdown()
    occ["int8_payload_ratio"] = round(
        occ["kv_pool_bytes_int8"] / occ["kv_pool_bytes_fp"], 3)
    out["kv_pool_occupancy"] = occ
    print("decode[kv_pool_occupancy]: " + ", ".join(
        f"{k}={v}" for k, v in occ.items()), flush=True)
    return out


def measure_overload(*, overflow: int = 12, seed: int = 0
                     ) -> Dict[str, Dict[str, float]]:
    """Overload-plane acceptance rows on the CPU tiny engine (admission
    control + deadline shedding, no serve stack in the way):

    - `overload_storm`: one bounded-queue engine (4 slots, queue cap
      8) saturated with long decodes, then hit with an expired-budget
      wave (must SHED before prefill) and an overflow wave (must be
      REJECTED with a retry-after hint).  Accounting is exact:
      offered == admitted + rejected + shed, the queue never exceeds
      its cap, and the block pool returns to its pre-storm free count.
    - `overload_ttft`: closed-loop 2x overload (2*slots in flight,
      n_new=1 so completion == first token): TTFT p50/p99 under
      sustained queueing.
    """
    import jax

    from ray_tpu import exceptions as exc
    from ray_tpu.models import llama
    from ray_tpu.serve.llm_engine import LlamaEngine

    cfg = llama.LlamaConfig.tiny(vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    slots, queue_cap, bs = 4, 8, 8
    out: Dict[str, Dict[str, float]] = {}

    def _prompt():
        return [int(x) for x in rng.integers(1, cfg.vocab_size, size=24)]

    eng = LlamaEngine(cfg, params, slots=slots, chunk=4, block_size=bs,
                      max_len=48, prefix_cache=False,
                      max_queued=queue_cap)
    try:
        # warm both compiled families (prefill bucket, chunk width)
        for f in [eng.submit(_prompt(), 8) for _ in range(slots)]:
            f.result(timeout=600)
        base = eng.stats()
        free0 = base["blocks_free"]
        t0 = time.perf_counter()
        # phase 1 — saturate every slot with a LONG decode (>= 6 chunk
        # dispatches), so nothing else can be admitted until they end
        long_futs = [eng.submit(_prompt(), 20) for _ in range(slots)]
        deadline = time.monotonic() + 60
        while eng.stats()["free_slots"] > 0:
            if time.monotonic() > deadline:
                raise RuntimeError("engine never saturated")
            time.sleep(0.001)
        # phase 2 — a wave with a ~zero budget: it QUEUES (the cap has
        # room) but every slot is busy for many chunk walls, so by pop
        # time the deadline is long past -> shed before prefill
        shed_futs = [eng.submit(_prompt(), 8, timeout_s=0.001)
                     for _ in range(6)]
        # phase 3 — overflow: more work than the queue cap can hold
        over_futs = [eng.submit(_prompt(), 8) for _ in range(overflow)]
        queue_peak = 0.0
        waves = long_futs + shed_futs + over_futs
        while not all(f.done() for f in waves):
            queue_peak = max(queue_peak, eng.stats()["queued"])
            time.sleep(0.002)
        wall = time.perf_counter() - t0
        admitted = rejected = shed = 0
        admitted_tokens = 0
        for f in waves:
            try:
                admitted_tokens += len(f.result(timeout=60))
                admitted += 1
            except exc.BackPressureError as e:
                assert e.retry_after_s > 0
                rejected += 1
            except exc.DeadlineExceededError:
                shed += 1
        s = eng.stats()
        out["overload_storm"] = {
            "offered": float(len(waves)),
            "admitted": float(admitted),
            "rejected": float(rejected),
            "shed": float(shed),
            "shed_expired": s["shed_expired"] - base["shed_expired"],
            "shed_predicted": (s["shed_predicted"]
                               - base["shed_predicted"]),
            "queue_cap": float(queue_cap),
            "queue_peak": queue_peak,
            "blocks_free_delta": float(s["blocks_free"] - free0),
            "prefill_calls": s["prefill_calls"] - base["prefill_calls"],
            "wall_s": round(wall, 3),
            "admitted_tok_s": round(admitted_tokens / wall, 1),
        }
        print("overload[storm]: " + ", ".join(
            f"{k}={v}" for k, v in out["overload_storm"].items()),
            flush=True)

        # -- TTFT under sustained 2x overload -------------------------
        target, conc = 32, 2 * slots
        lat: List[float] = []
        inflight: List[tuple] = []
        submitted = 0
        t0 = time.perf_counter()
        while len(lat) < target:
            while submitted < target and len(inflight) < conc:
                inflight.append((time.perf_counter(),
                                 eng.submit(_prompt(), 1)))
                submitted += 1
            t_s, f = inflight.pop(0)
            f.result(timeout=600)
            lat.append(time.perf_counter() - t_s)
        wall = time.perf_counter() - t0
        out["overload_ttft"] = {
            "requests": float(target),
            "concurrency": float(conc),
            "ttft_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
            "ttft_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            "tok_s": round(target / wall, 1),
        }
        print("overload[ttft]: " + ", ".join(
            f"{k}={v}" for k, v in out["overload_ttft"].items()),
            flush=True)
    finally:
        eng.shutdown()
    return out


def _elastic_mttr_loop(config):
    """Per-worker loop for `--elastic-recovery`: pure control-plane
    (no jax) so the measured MTTR is detection + re-form + restore,
    not model compile time.  Rank 1 SIGKILLs itself mid-step on the
    first attempt, recording the kill instant for the driver."""
    import os as _os
    import signal as _signal

    from ray_tpu import train as rtrain
    from ray_tpu.train.checkpoint import Checkpoint as _Ck

    ctx = rtrain.get_context()
    ck = rtrain.get_checkpoint()
    start = ck.to_dict()["step"] + 1 if ck is not None else 0
    for step in range(start, config["num_steps"]):
        if (ck is None and step == config["kill_at"]
                and ctx.get_world_rank() == 1):
            with open(config["kill_marker"], "w") as f:
                f.write(repr(time.time()))
            _os.kill(_os.getpid(), _signal.SIGKILL)
        c = (_Ck.from_dict({"step": step})
             if ctx.get_world_rank() == 0 else None)
        rtrain.report({"step": step, "world": ctx.get_world_size()},
                      checkpoint=c)


def measure_elastic_recovery(*, num_workers: int = 2, num_steps: int = 12,
                             kill_at: int = 4) -> Dict[str, Dict[str, float]]:
    """MTTR for elastic preemption recovery (docs/elastic_training.md):
    SIGKILL one training rank mid-step and measure, on the wall clock,

    - `detect_s`: kill → the health plane marking the rank lost;
    - `mttr_s`:   kill → the FIRST post-recovery step reported by the
      re-formed group (detection + drain + re-reserve + actor boot +
      checkpoint restore);
    - `resume_step` == the checkpointed step (no lost progress beyond
      the in-flight step).

    The structural shape of these rows is tier-1-gated
    (`tests/test_perf_harness.py`); the measured numbers live in
    PERF.md."""
    import tempfile

    from ray_tpu.train import (
        FailureConfig, JaxConfig, JaxTrainer, RunConfig, ScalingConfig,
    )

    workdir = tempfile.mkdtemp(prefix="rt_elastic_mttr_")
    kill_marker = os.path.join(workdir, "kill_ts")
    reports: List[Dict[str, float]] = []
    trainer = JaxTrainer(
        _elastic_mttr_loop,
        train_loop_config={
            "num_steps": num_steps, "kill_at": kill_at,
            "kill_marker": kill_marker,
        },
        jax_config=JaxConfig(distributed_mode="none"),
        scaling_config=ScalingConfig(num_workers=num_workers),
        run_config=RunConfig(
            storage_path=workdir, name="elastic_mttr",
            failure_config=FailureConfig(
                elastic=True, min_workers=1, detect_poll_s=0.2,
                drain_timeout_s=3.0, reform_timeout_s=10.0,
            ),
        ),
    )
    trainer._result_callback = lambda m, ck: reports.append(
        {"step": m["step"], "wall": time.time()}
    )
    if num_workers < 2:
        raise ValueError(
            "--elastic-workers must be >= 2: the harness SIGKILLs "
            "rank 1, which does not exist in a 1-worker group"
        )
    result = trainer.fit()
    if result.error is not None:
        raise RuntimeError(f"elastic recovery run failed: {result.error}")
    shrinks = [e for e in trainer._elastic_events if e["kind"] == "shrink"]
    reforms = [e for e in trainer._elastic_events if e["kind"] == "reform"]
    if not shrinks or not reforms or not os.path.exists(kill_marker):
        raise RuntimeError(
            "elastic recovery run exercised no failover (events: "
            f"{trainer._elastic_events}) — nothing to measure"
        )
    with open(kill_marker) as f:
        kill_wall = float(f.read())
    shrink, reform = shrinks[0], reforms[0]
    # the resumed step re-reports the checkpointed step + 1: the first
    # report after the reform event is the first post-recovery step
    post = [r for r in reports if r["wall"] >= reform["wall"]]
    resume_step = post[0]["step"] if post else -1
    row = {
        "detect_s": round(shrink["detected_wall"] - kill_wall, 3),
        "mttr_s": round(post[0]["wall"] - kill_wall, 3) if post else -1.0,
        "reform_s": round(reform["wall"] - shrink["detected_wall"], 3),
        "kill_step": float(kill_at),
        "resume_step": float(resume_step),
        "final_step": float(result.metrics["step"]),
        "failovers": float(sum(1 for e in trainer._elastic_events
                               if e["kind"] == "shrink")),
        "reform_width": float(reform["width"]),
    }
    print("elastic_recovery: " + ", ".join(
        f"{k}={v}" for k, v in row.items()), flush=True)
    return {"elastic_recovery": row}


class _DagPerfWorker:
    """Module-level so the actor class pickles by reference."""

    def double(self, x):
        return 2 * x


def measure_dag_calls(*, n: int = 2000, tensor_mb: float = 4.0,
                      num_workers: int = 2
                      ) -> Dict[str, Dict[str, float]]:
    """`--config dag_calls`: the compiled-DAG fast plane vs the 1:1
    actor-call plane, measured head-to-head in one cluster:

    - actor_us_per_call: rt.get(actor.method.remote(x)) round trip —
      the full submit/lease/complete machinery per call;
    - dag_us_per_call: compiled execute(x).get() round trip — channel
      ops only (the resident exec loop bypasses the RPC plane);
    - tensor_inline_mb_s / tensor_spill_mb_s: one-way tensor-channel
      bandwidth for a slot-sized array and a spill-path array (raw
      buffer bytes, no pickle).

    Structural shape tier-1-gated in tests/test_perf_harness.py;
    measured numbers live in PERF.md."""
    import numpy as np

    import ray_tpu as rt

    if rt.is_initialized():
        raise RuntimeError(
            "--config dag_calls boots its own cluster: run with no "
            "runtime initialized"
        )
    rt.init(num_workers=num_workers, num_cpus=8)
    try:
        from ray_tpu.dag import InputNode
        from ray_tpu.dag.channel import Channel

        w = rt.remote(_DagPerfWorker).remote()
        rt.get(w.double.remote(0))  # warm the lease + worker
        t0 = time.perf_counter()
        for i in range(n):
            rt.get(w.double.remote(i))
        actor_s = time.perf_counter() - t0

        with InputNode() as inp:
            dag = w.double.bind(inp)
        compiled = dag.experimental_compile()
        try:
            assert compiled.execute(1).get() == 2  # warm the channels
            t0 = time.perf_counter()
            for i in range(n):
                compiled.execute(i).get()
            dag_s = time.perf_counter() - t0
        finally:
            compiled.teardown()

        def chan_bw(name: str, arr) -> float:
            ch = Channel(name)
            reps = max(4, int(64 * 1024 * 1024 / max(1, arr.nbytes)))
            ch.write(arr)
            assert ch.read(timeout_s=30).shape == arr.shape  # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                ch.write(arr)
                ch.read(timeout_s=30)
            wall = time.perf_counter() - t0
            ch.destroy()
            return (reps * arr.nbytes) / wall / 1e6

        inline = np.zeros(64 * 1024 // 4, np.float32)  # fits one slot
        spill = np.zeros(int(tensor_mb * 1024 * 1024 / 4), np.float32)
        row = {
            "calls": float(n),
            "actor_us_per_call": 1e6 * actor_s / n,
            "dag_us_per_call": 1e6 * dag_s / n,
            "speedup": actor_s / dag_s,
            "tensor_inline_mb_s": chan_bw("perf_dag_inline", inline),
            "tensor_spill_mb_s": chan_bw("perf_dag_spill", spill),
        }
        print(
            f"dag_calls: actor {row['actor_us_per_call']:.1f} us/call, "
            f"compiled {row['dag_us_per_call']:.1f} us/call "
            f"({row['speedup']:.1f}x), tensor chan "
            f"{row['tensor_inline_mb_s']:.0f} MB/s inline / "
            f"{row['tensor_spill_mb_s']:.0f} MB/s spill",
            flush=True,
        )
        return {"dag_calls": row}
    finally:
        rt.shutdown()


def measure_data_shuffle(*, rows: int = 3_200_000,
                         store_mb: int = 12,
                         integrity: str = "on"
                         ) -> Dict[str, Dict[str, float]]:
    """`--config data_shuffle`: throughput of a repartition+sort
    exchange over a dataset ~2x the object-store budget — the
    distributed shuffle must complete THROUGH the spilling plane
    (pinned in-flight bytes bounded by the store-aware stage budget,
    `data/shuffle.py`), with exact row accounting.  Structural shape
    tier-1-gated in `tests/test_perf_harness.py`; measured numbers
    live in PERF.md.

    `integrity` gates the object-plane checksum plane (spill-time CRC
    + verify-on-restore, `core/integrity.py`): "on" (the default) or
    "off" — run both and compare to measure the spill-path checksum
    overhead honestly (the ≤5% budget claim in PERF.md)."""
    import glob

    import numpy as np

    import ray_tpu as rt
    import ray_tpu.api as api
    import ray_tpu.data as rd

    if rt.is_initialized():
        raise RuntimeError(
            "--config data_shuffle sizes its own object store: run "
            "with no runtime initialized"
        )
    store_bytes = store_mb * 1024 * 1024
    dataset_bytes = rows * 8  # one int64 column
    # the spill path lives in the DAEMON: the knob must ride the env
    prior_integrity = os.environ.get("RT_OBJECT_INTEGRITY")
    os.environ["RT_OBJECT_INTEGRITY"] = (
        "1" if integrity != "off" else "0"
    )
    rt.init(num_workers=2, num_cpus=4, object_store_memory=store_bytes)
    try:
        ds = rd.range(rows, parallelism=12).repartition(8).sort(
            "id", descending=True
        )
        t0 = time.perf_counter()
        total = 0
        checksum = 0
        ordered = True
        prev = None
        for batch in ds.iter_batches(batch_size=200_000):
            ids = batch["id"]
            total += len(ids)
            checksum += int(ids.sum())
            if np.any(np.diff(ids) > 0) or (
                prev is not None and ids[0] > prev
            ):
                ordered = False
            prev = int(ids[-1])
        elapsed = time.perf_counter() - t0
        sd = api._session.get("session_dir")
        spill_bytes = sum(
            os.path.getsize(f) for f in glob.glob(f"{sd}/spilled/*.bin")
        )
        row = {
            "rows": float(rows),
            "rows_per_s": round(total / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "dataset_bytes": float(dataset_bytes),
            "store_bytes": float(store_bytes),
            "store_ratio": round(dataset_bytes / store_bytes, 2),
            "spill_bytes": float(spill_bytes),
            "rows_out": float(total),
            "rows_exact": float(
                total == rows and checksum == rows * (rows - 1) // 2
            ),
            "globally_sorted": float(ordered),
            "integrity_on": float(integrity != "off"),
        }
    finally:
        rt.shutdown()
        if prior_integrity is None:
            os.environ.pop("RT_OBJECT_INTEGRITY", None)
        else:
            os.environ["RT_OBJECT_INTEGRITY"] = prior_integrity
    key = ("data_shuffle" if integrity != "off"
           else "data_shuffle_integrity_off")
    print(f"{key}: " + ", ".join(
        f"{k}={v}" for k, v in row.items()), flush=True)
    return {key: row}


def measure_storage_faults(*, rows: int = 2_000_000, store_mb: int = 8,
                           seed: int = 1313
                           ) -> Dict[str, Dict[str, float]]:
    """`--config storage_faults`: the chaos-matrix row — a seeded
    schedule of bit-flip + ENOSPC + EIO disk faults injected at the
    `core/diskio.py` chokepoint under a repartition+sort epoch of a
    dataset ~2x the object store.  The epoch must complete with EXACT
    row accounting despite corrupt spilled files (quarantine + lineage
    re-derivation) and intermittently refused/failing spill I/O
    (un-election + restore retries + typed backpressure clamps).

    The fault schedule is fully determined by `seed` (replay a failure
    with `--storage-faults-seed <seed>` — the seed is printed on every
    run and embedded in the assertion message on failure).  Structural
    shape tier-1-gated in `tests/test_perf_harness.py`."""
    import urllib.request

    import ray_tpu as rt
    import ray_tpu.data as rd

    if rt.is_initialized():
        raise RuntimeError(
            "--config storage_faults sizes its own object store and "
            "fault schedule: run with no runtime initialized"
        )
    chaos = {
        # every ~2nd spilled file silently corrupted; restores verify,
        # quarantine, and fall through to lineage
        "bit_flip_prob": 0.5,
        # transient device errors on the spill plane (reads retry
        # through the backoff schedule; writes un-elect)
        "eio_prob": 0.25,
        # occasional disk-full refusals (pass aborts + latch clears
        # when a later free-bytes check passes)
        "enospc_prob": 0.1,
        "match": "spilled",
        "seed": int(seed),
    }
    print(f"storage_faults: seed={seed} chaos={chaos}", flush=True)
    prior = os.environ.get("RT_DISK_CHAOS")
    os.environ["RT_DISK_CHAOS"] = json.dumps(chaos)
    from ray_tpu.core import diskio as _diskio

    _diskio.set_disk_chaos(None)
    _diskio._chaos_env_checked = False
    store_bytes = store_mb * 1024 * 1024
    try:
        rt.init(num_workers=2, num_cpus=4,
                object_store_memory=store_bytes,
                _system_config={"metrics_http_port": -1})
        t0 = time.perf_counter()
        ds = rd.range(rows, parallelism=10).repartition(6).sort(
            "id", descending=True
        )
        total = 0
        checksum = 0
        for batch in ds.iter_batches(batch_size=250_000):
            ids = batch["id"]
            total += len(ids)
            checksum += int(ids.sum())
        elapsed = time.perf_counter() - t0
        # fault evidence from the daemon's /metrics (fault counters
        # bypass the metrics_enabled gate)
        counters: Dict[str, float] = {}
        from ray_tpu.core.runtime import get_runtime

        for n in get_runtime().controller_call("get_nodes"):
            port = n.get("metrics_port")
            if not n.get("alive") or not port:
                continue
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=15
            ) as r:
                for line in r.read().decode().splitlines():
                    for m in ("rt_object_integrity_errors_total",
                              "rt_object_quarantined_total",
                              "rt_spill_disk_full_total",
                              "rt_spill_errors_total"):
                        if line.startswith(m):
                            counters[m] = counters.get(m, 0.0) + float(
                                line.rsplit(" ", 1)[1]
                            )
        rows_exact = (total == rows
                      and checksum == rows * (rows - 1) // 2)
        assert rows_exact, (
            f"storage_faults row accounting broke under the fault "
            f"schedule: rows_out={total} (expected {rows}); replay "
            f"with --storage-faults-seed {seed}"
        )
        row = {
            "rows": float(rows),
            "rows_per_s": round(total / elapsed, 1),
            "elapsed_s": round(elapsed, 3),
            "store_ratio": round(rows * 8 / store_bytes, 2),
            "rows_exact": 1.0,
            "seed": float(seed),
            "integrity_errors": counters.get(
                "rt_object_integrity_errors_total", 0.0),
            "quarantined": counters.get(
                "rt_object_quarantined_total", 0.0),
            "spill_disk_full": counters.get(
                "rt_spill_disk_full_total", 0.0),
            "spill_io_errors": counters.get(
                "rt_spill_errors_total", 0.0),
        }
    finally:
        rt.shutdown()
        if prior is None:
            os.environ.pop("RT_DISK_CHAOS", None)
        else:
            os.environ["RT_DISK_CHAOS"] = prior
        _diskio.set_disk_chaos(None)
    print("storage_faults: " + ", ".join(
        f"{k}={v}" for k, v in row.items()), flush=True)
    return {"storage_faults": row}


def measure_obs_overhead(*, storm_n: int = 3000, rounds: int = 6,
                         num_workers: int = 2) -> Dict[str, Dict[str, float]]:
    """`--config obs_overhead`: throughput cost of the unified
    observability plane on the task-storm hot path.

    Methodology — alternating in-cluster A/B, medians compared: the
    storm benchmark's variance is large (cluster-to-cluster ±3-5%,
    storm-to-storm inside one cluster ±10% — an off-vs-off control
    shows a ±4% phantom 'overhead'), which no single comparison can
    resolve against a 3% budget.  One cluster boots with
    `RT_METRICS_ENABLED=1` propagated to every process, so the batched
    reporting loops (driver/worker/daemon obs frames, store-gauge
    refresh) run for the WHOLE measurement as constant background;
    after two full-size warm storms, `rounds` alternating off/on
    storms run with the driver-side gate flipped between them — every
    per-task instrumented path (owner submit counter, completion
    counter + latency histogram, lease metrics, obs-frame assembly)
    lives in the driver, so the gate isolates exactly the per-task
    cost, alternation cancels drift, and comparing group MEDIANS
    suppresses the per-storm outliers.  The 'on' phases self-validate
    that instrumentation actually fired (the completion counter grows
    by at least the storm size), so the number can never silently
    measure a disabled plane.  Structural shape tier-1-gated in
    `tests/test_perf_harness.py`; the measured <3% budget claim lives
    in PERF.md."""
    import statistics as _stats

    import ray_tpu as rt
    from ray_tpu.metrics import metric_defs as _md

    if rt.is_initialized():
        raise RuntimeError(
            "--config obs_overhead boots its own cluster: run with "
            "no runtime initialized"
        )

    def _completed() -> float:
        return sum(v for _, v in _md.metric(
            "rt_owner_tasks_completed_total")._samples())

    prior_env = os.environ.get("RT_METRICS_ENABLED")
    _md.set_enabled(True)  # children inherit: reporting loops run
    rt.init(num_workers=num_workers,
            num_cpus=max(8, 2 * num_workers),
            _system_config={"metrics_enabled": True})
    off_tps: List[float] = []
    on_tps: List[float] = []
    instrumented = True
    try:
        # two FULL-SIZE warm storms: the first storms of a fresh
        # cluster run far from steady state (lease ramp, allocator)
        measure_task_storm(rt, n=storm_n)
        measure_task_storm(rt, n=storm_n)
        for _ in range(rounds):
            _md.set_enabled(False)
            off_tps.append(measure_task_storm(rt, n=storm_n)["tasks_per_s"])
            _md.set_enabled(True)
            before = _completed()
            on_tps.append(measure_task_storm(rt, n=storm_n)["tasks_per_s"])
            instrumented &= (_completed() - before) >= storm_n
    finally:
        rt.shutdown()
        # restore BOTH halves of the gate: module flag to what the
        # caller's environment implies, then the env var itself (a
        # process started with the flag on must leave with it on)
        _md.set_enabled(prior_env in ("1", "true", "True"))
        if prior_env is not None:
            os.environ["RT_METRICS_ENABLED"] = prior_env
    med_off = _stats.median(off_tps)
    med_on = _stats.median(on_tps)
    out: Dict[str, Dict[str, float]] = {
        "metrics_off": {
            "tasks_per_s": round(med_off, 1),
            "tasks_per_s_min": round(min(off_tps), 1),
            "tasks_per_s_max": round(max(off_tps), 1),
            "rounds": float(rounds), "storm_n": float(storm_n),
        },
        "metrics_on": {
            "tasks_per_s": round(med_on, 1),
            "tasks_per_s_min": round(min(on_tps), 1),
            "tasks_per_s_max": round(max(on_tps), 1),
            "rounds": float(rounds), "storm_n": float(storm_n),
            "instrumented": float(instrumented),
        },
        "obs_overhead": {
            "overhead_pct": round(100.0 * (1.0 - med_on / med_off), 2),
            "metrics_off_tasks_per_s": round(med_off, 1),
            "metrics_on_tasks_per_s": round(med_on, 1),
            "instrumented": float(instrumented),
        },
    }
    for k in ("metrics_off", "metrics_on", "obs_overhead"):
        print(f"obs_overhead[{k}]: " + ", ".join(
            f"{kk}={vv}" for kk, vv in out[k].items()), flush=True)
    return out


def measure_serve_obs_overhead(*, requests: int = 24, n_new: int = 8,
                               rounds: int = 6, seed: int = 0,
                               ) -> Dict[str, Dict[str, float]]:
    """The serve-path half of `--config obs_overhead`: throughput cost
    of the per-request ledger + phase histograms on the continuous-
    batching hot path (CPU tiny model, in-process engine, no cluster).

    Same alternating-median methodology as the task-storm half: ONE
    engine serves every round, 'off' and 'on' storms alternate with the
    driver-side metrics gate flipped between them.  The driver loop is
    byte-identical in both phases — it always calls `start_request` and
    wraps the submit in `use_ledger` — so the gate alone decides the
    cost: gate down, `start_request` returns None and the engine's
    `engine_ticket()` returns None (the zero-allocation path the unit
    tests pin); gate up, every request carries a live ledger and the
    engine stamps admission/prefill/first-token/done onto its ticket,
    with phase histograms observed at finish.  The 'on' phases
    self-validate through the e2e histogram count (every storm request
    must land one observation — the row can never measure a disabled
    ledger).  Budget: <=2% on serve tok/s, recorded in PERF.md."""
    import statistics as _stats

    import jax

    from ray_tpu.metrics import metric_defs as _md
    from ray_tpu.models import llama
    from ray_tpu.serve import request_ledger as _rl
    from ray_tpu.serve.llm_engine import LlamaEngine

    cfg = llama.LlamaConfig.tiny(vocab_size=256)
    params = llama.init_params(cfg, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    prompts = [
        [int(x) for x in rng.integers(1, cfg.vocab_size, size=24)]
        for _ in range(requests)
    ]

    def _e2e_count() -> float:
        return sum(
            v for labels, v in
            _md.metric("rt_serve_e2e_seconds")._samples()
            if "__count__" in labels
        )

    def _storm(eng) -> float:
        futs = []
        ledgers = []
        t0 = time.perf_counter()
        for p in prompts:
            led = _rl.start_request("bench", "perf", "obs", replica="r0")
            with _rl.use_ledger(led):
                futs.append(eng.submit(list(p), n_new))
            ledgers.append(led)
        for f, led in zip(futs, ledgers):
            f.result(timeout=600)
            if led is not None:
                led.finish("ok")
        return requests * n_new / (time.perf_counter() - t0)

    prior_env = os.environ.get("RT_METRICS_ENABLED")
    off_tps: List[float] = []
    on_tps: List[float] = []
    instrumented = True
    eng = LlamaEngine(cfg, params, slots=4, chunk=4, block_size=8,
                      max_len=48)
    try:
        _md.set_enabled(False)
        _storm(eng)  # warm compiles (both prefill routes stay warm)
        _storm(eng)
        for _ in range(rounds):
            _md.set_enabled(False)
            off_tps.append(_storm(eng))
            _md.set_enabled(True)
            before = _e2e_count()
            on_tps.append(_storm(eng))
            instrumented &= (_e2e_count() - before) >= requests
    finally:
        eng.shutdown()
        _md.set_enabled(prior_env in ("1", "true", "True"))
        if prior_env is not None:
            os.environ["RT_METRICS_ENABLED"] = prior_env
    med_off = _stats.median(off_tps)
    med_on = _stats.median(on_tps)
    out: Dict[str, Dict[str, float]] = {
        "serve_obs_off": {
            "tokens_per_sec": round(med_off, 1),
            "tokens_per_sec_min": round(min(off_tps), 1),
            "tokens_per_sec_max": round(max(off_tps), 1),
            "rounds": float(rounds), "requests": float(requests),
        },
        "serve_obs_on": {
            "tokens_per_sec": round(med_on, 1),
            "tokens_per_sec_min": round(min(on_tps), 1),
            "tokens_per_sec_max": round(max(on_tps), 1),
            "rounds": float(rounds), "requests": float(requests),
            "instrumented": float(instrumented),
        },
        "serve_obs_overhead": {
            "overhead_pct": round(100.0 * (1.0 - med_on / med_off), 2),
            "ledger_off_tokens_per_sec": round(med_off, 1),
            "ledger_on_tokens_per_sec": round(med_on, 1),
            "instrumented": float(instrumented),
        },
    }
    for k in ("serve_obs_off", "serve_obs_on", "serve_obs_overhead"):
        print(f"obs_overhead[{k}]: " + ", ".join(
            f"{kk}={vv}" for kk, vv in out[k].items()), flush=True)
    return out


def main(argv: Optional[List[str]] = None) -> Dict[str, Dict[str, float]]:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--filter", default=None, help="substring filter")
    p.add_argument("--json", default=None, help="write results to file")
    p.add_argument("--rounds", type=int, default=3)
    p.add_argument("--round-sec", type=float, default=1.0)
    p.add_argument("--num-workers", type=int, default=4)
    p.add_argument("--storm", action="store_true",
                   help="also measure the 1k-task storm latency "
                        "distribution (scheduling throughput bound)")
    p.add_argument("--storm-n", type=int, default=1000)
    p.add_argument("--owner-shards", type=int, default=0,
                   help="driver-side owner shards (0 = config default; "
                        "N>1 runs N submission/completion loops keyed "
                        "by task id — docs/control_plane.md); storm and "
                        "envelope-queue rows report per-shard us/task")
    p.add_argument("--core-split", action="store_true",
                   help="task storm with per-plane CPU accounting + "
                        "multi-core pipeline projection")
    p.add_argument("--pin-cores", type=int, default=0,
                   help="pin driver/daemon/workers to dedicated cores "
                        "(needs a box with that many cores)")
    p.add_argument("--busbw", action="store_true",
                   help="also measure host ring-allreduce bus bandwidth")
    p.add_argument("--busbw-world", type=int, default=2)
    p.add_argument("--busbw-mb", type=int, default=16)
    p.add_argument("--engine-trace", action="store_true",
                   help="serve LLM engine tick-trace rows INSTEAD of "
                        "the matrix: paged-KV budget invariance, radix "
                        "prefix reuse, CB smoke (CPU tiny model; no "
                        "cluster)")
    p.add_argument("--engine-requests", type=int, default=24)
    p.add_argument("--overload", action="store_true",
                   help="overload-plane rows (no cluster): bounded-"
                        "queue storm accounting (offered vs admitted "
                        "vs rejected vs shed, block-pool leak check) "
                        "and TTFT p50/p99 under 2x overload")
    p.add_argument("--overload-overflow", type=int, default=12)
    p.add_argument("--elastic-recovery", action="store_true",
                   help="measure elastic-training MTTR: SIGKILL one "
                        "rank mid-step, report kill->detect and "
                        "kill->first-post-recovery-step latencies")
    p.add_argument("--elastic-workers", type=int, default=2)
    p.add_argument("--elastic-steps", type=int, default=12)
    p.add_argument("--config", default=None,
                   choices=["data_shuffle", "obs_overhead",
                            "storage_faults", "rllib_ppo", "dag_calls",
                            "decode_kernel"],
                   help="named measurement config (data_shuffle: "
                        "repartition+sort of a dataset ~2x the object "
                        "store, rows/s + spill bytes; obs_overhead: "
                        "task-storm throughput with the metrics plane "
                        "off vs on, overhead pct, plus the serve-path "
                        "A/B (request ledger + phase histograms on vs "
                        "off on the CB engine); storage_faults: the "
                        "same exchange under a seeded bit-flip + "
                        "ENOSPC + EIO disk-fault schedule, exact row "
                        "accounting + fault-counter evidence; "
                        "rllib_ppo: EnvRunner fleet -> pjit learner "
                        "gang with async overlap, env-steps/s + "
                        "updates/s + exactly-once ledger accounting; "
                        "dag_calls: compiled-DAG round trip vs the 1:1 "
                        "actor-call plane + tensor-channel MB/s; "
                        "decode_kernel: bare-decode fused paged-"
                        "attention kernel vs gather route at several "
                        "batch sizes + int8 vs bf16 pool occupancy)")
    p.add_argument("--decode-batches", default="16,32,64",
                   help="decode_kernel: comma-separated batch sizes")
    p.add_argument("--dag-calls-n", type=int, default=2000,
                   help="dag_calls: round trips per plane")
    p.add_argument("--dag-tensor-mb", type=float, default=4.0,
                   help="dag_calls: spill-path tensor size (MB)")
    p.add_argument("--rllib-runners", type=int, default=4)
    p.add_argument("--rllib-envs-per-runner", type=int, default=8)
    p.add_argument("--rllib-rollout-len", type=int, default=32)
    p.add_argument("--rllib-gang-devices", type=int, default=2)
    p.add_argument("--rllib-iters", type=int, default=3)
    p.add_argument("--shuffle-rows", type=int, default=3_200_000)
    p.add_argument("--shuffle-store-mb", type=int, default=12)
    p.add_argument("--shuffle-integrity", default="on",
                   choices=["on", "off", "both"],
                   help="object-plane checksums during data_shuffle; "
                        "'both' runs on-then-off for the overhead "
                        "comparison recorded in PERF.md")
    p.add_argument("--storage-faults-seed", type=int, default=1313,
                   help="replay seed for the storage_faults chaos "
                        "schedule (printed on every run)")
    p.add_argument("--storage-faults-rows", type=int, default=2_000_000)
    p.add_argument("--storage-faults-store-mb", type=int, default=8)
    p.add_argument("--obs-storm-n", type=int, default=3000)
    p.add_argument("--obs-rounds", type=int, default=6)
    p.add_argument("--obs-serve-requests", type=int, default=24,
                   help="obs_overhead: requests per serve-path A/B "
                        "storm (ledger+histograms on vs off on the "
                        "in-process CB engine)")
    p.add_argument("--envelope", action="store_true",
                   help="run the scalability-envelope rows INSTEAD of "
                        "the microbenchmark matrix (reference: "
                        "release/benchmarks/single_node)")
    p.add_argument("--envelope-rows", default="args,returns,get,queue,large",
                   help="comma list: args,returns,get,queue,large,broadcast")
    p.add_argument("--envelope-args-n", type=int, default=10_000)
    p.add_argument("--envelope-returns-n", type=int, default=3_000)
    p.add_argument("--envelope-get-n", type=int, default=10_000)
    p.add_argument("--envelope-queue-n", type=int, default=100_000)
    p.add_argument("--envelope-large-gb", type=float, default=50.0)
    p.add_argument("--envelope-bcast-nodes", type=int, default=4)
    p.add_argument("--envelope-bcast-gb", type=float, default=1.0)
    args = p.parse_args(argv)

    # kill -USR1 <pid> dumps all thread stacks — the only way to see
    # where a wedged run is stuck on a box with no gdb/py-spy
    import faulthandler
    import signal

    faulthandler.register(signal.SIGUSR1)

    if args.config == "data_shuffle":
        results = {}
        modes = (["on", "off"] if args.shuffle_integrity == "both"
                 else [args.shuffle_integrity])
        for mode in modes:
            results.update(measure_data_shuffle(
                rows=args.shuffle_rows, store_mb=args.shuffle_store_mb,
                integrity=mode,
            ))
        if len(modes) == 2:
            on = results["data_shuffle"]["rows_per_s"]
            off = results["data_shuffle_integrity_off"]["rows_per_s"]
            results["integrity_overhead"] = {
                "overhead_pct": round(100.0 * (1.0 - on / off), 2),
                "integrity_on_rows_per_s": on,
                "integrity_off_rows_per_s": off,
            }
            print("integrity_overhead: " + ", ".join(
                f"{k}={v}"
                for k, v in results["integrity_overhead"].items()
            ), flush=True)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.config == "storage_faults":
        results = measure_storage_faults(
            rows=args.storage_faults_rows,
            store_mb=args.storage_faults_store_mb,
            seed=args.storage_faults_seed,
        )
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.config == "dag_calls":
        results = measure_dag_calls(
            n=args.dag_calls_n, tensor_mb=args.dag_tensor_mb,
            num_workers=args.num_workers,
        )
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.config == "rllib_ppo":
        from ray_tpu.rllib.bench import measure_rllib_ppo

        results = measure_rllib_ppo(
            num_runners=args.rllib_runners,
            envs_per_runner=args.rllib_envs_per_runner,
            rollout_len=args.rllib_rollout_len,
            minibatch=max(
                64,
                args.rllib_envs_per_runner * args.rllib_rollout_len,
            ),
            gang_devices=args.rllib_gang_devices,
            iters=args.rllib_iters,
            compare_sync=False,
        )
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.config == "decode_kernel":
        # no cluster: engines are driven in-process on the local backend
        batches = tuple(
            int(x) for x in str(args.decode_batches).split(",") if x
        )
        results = measure_decode_kernel(batches=batches)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.config == "obs_overhead":
        results = measure_obs_overhead(
            storm_n=args.obs_storm_n, rounds=args.obs_rounds,
            num_workers=args.num_workers,
        )
        # serve-path half: runs after the cluster is down (in-process
        # engine, no runtime needed)
        results.update(measure_serve_obs_overhead(
            requests=args.obs_serve_requests, rounds=args.obs_rounds,
        ))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.engine_trace or args.overload:
        # no cluster: the engine is driven in-process on the CPU backend
        results = {}
        if args.engine_trace:
            results.update(measure_engine_trace(
                requests=args.engine_requests
            ))
        if args.overload:
            results.update(measure_overload(
                overflow=args.overload_overflow
            ))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    import ray_tpu as rt

    sysconf = (
        {"owner_shards": args.owner_shards} if args.owner_shards else None
    )

    if args.elastic_recovery:
        owns = not rt.is_initialized()
        if owns:
            rt.init(num_workers=max(4, args.elastic_workers * 2),
                    num_cpus=max(8, args.elastic_workers * 2),
                    _system_config=sysconf)
        try:
            results = measure_elastic_recovery(
                num_workers=args.elastic_workers,
                num_steps=args.elastic_steps,
            )
        finally:
            if owns:
                rt.shutdown()
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    if args.envelope:
        rows = [r.strip() for r in args.envelope_rows.split(",") if r.strip()]
        results = {}
        single_rows = [r for r in rows if r != "broadcast"]
        if single_rows:
            store = None
            if "large" in rows:
                store = int((args.envelope_large_gb + 4) * (1 << 30))
            if rt.is_initialized():
                raise RuntimeError(
                    "--envelope sizes its own object store: run with "
                    "no runtime initialized"
                )
            rt.init(num_workers=args.num_workers,
                    num_cpus=max(16, args.num_workers * 2),
                    object_store_memory=store,
                    _system_config=sysconf)
            try:
                results.update(measure_envelope(
                    rt, rows=single_rows,
                    args_n=args.envelope_args_n,
                    returns_n=args.envelope_returns_n,
                    get_n=args.envelope_get_n,
                    queue_n=args.envelope_queue_n,
                    large_gb=args.envelope_large_gb,
                    num_workers=args.num_workers,
                ))
            finally:
                rt.shutdown()
        if "broadcast" in rows:
            results["broadcast"] = measure_envelope_broadcast(
                n_nodes=args.envelope_bcast_nodes,
                size_gb=args.envelope_bcast_gb,
            )
            print("envelope[broadcast]: " + ", ".join(
                f"{k}={v}" for k, v in results["broadcast"].items()),
                flush=True)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(results, f, indent=2)
        print(json.dumps(results))
        return results

    owns = not rt.is_initialized()
    if owns:
        rt.init(num_workers=args.num_workers, num_cpus=max(
            16, args.num_workers * 2
        ), _system_config=sysconf)
    results: Dict[str, Dict[str, float]] = {}
    try:
        if args.pin_cores:
            # warm the worker pool BEFORE pinning: workers spawned
            # after pinning inherit the daemon's core
            measure_task_storm(rt, n=100)
            placement = apply_core_pinning(args.pin_cores)
            print(f"pinned planes to cores: {placement}", flush=True)
        if args.core_split:
            split = measure_core_split(rt, n=args.storm_n)
            print(
                f"core split ({args.storm_n} tasks): "
                + ", ".join(
                    f"{k.split('_')[0]} {split[k]}us"
                    for k in ("driver_us_per_task", "noded_us_per_task",
                              "worker_us_per_task", "other_us_per_task")
                )
                + f" | measured {split['measured_tasks_per_s']}/s, "
                f"pipelined-projection "
                f"{split['projected_pipelined_tasks_per_s']}/s "
                f"(bottleneck: {split['bottleneck']})",
                flush=True,
            )
            results["core_split"] = {
                k: v for k, v in split.items() if isinstance(v, float)
            }
            results["core_split"]["bottleneck"] = split["bottleneck"]  # type: ignore[assignment]
        for name, factory, mult in build_matrix(rt, args):
            if args.filter and args.filter not in name:
                continue
            body, cleanup = factory()
            try:
                n, mean, sd = timeit(name, body, mult, rounds=args.rounds,
                                     round_sec=args.round_sec)
            finally:
                cleanup()
            results[n] = {"ops_per_s": round(mean, 2), "sd": round(sd, 2)}
        if args.storm:
            shards_before = _shard_snapshot()
            dist = measure_task_storm(rt, n=args.storm_n)
            shard_rows = owner_shard_report(shards_before, _shard_snapshot())
            print(
                f"task storm ({args.storm_n} tasks): "
                f"submit {dist['submit_s']:.2f}s, drain "
                f"{dist['drain_s']:.2f}s, latency p50 {dist['p50_s']:.2f}s "
                f"p95 {dist['p95_s']:.2f}s p100 {dist['p100_s']:.2f}s",
                flush=True,
            )
            for row in shard_rows:
                print(
                    f"  owner shard {row['shard']}: "
                    f"{row['completed']} tasks, "
                    f"{row['cpu_s']:.2f}s CPU, "
                    f"{row['us_per_task']:.0f} us/task",
                    flush=True,
                )
            results["task_storm"] = {
                k: round(v, 3) for k, v in dist.items()
            }
            results["task_storm"]["owner_shards"] = shard_rows  # type: ignore[assignment]
        if args.busbw:
            bw = measure_allreduce_busbw(
                rt, world=args.busbw_world, size_mb=args.busbw_mb
            )
            print(f"allreduce busbw ({args.busbw_world} ranks, "
                  f"{args.busbw_mb} MB): {bw:.2f} GB/s", flush=True)
            results["allreduce_busbw_gbps"] = {"ops_per_s": round(bw, 3),
                                               "sd": 0.0}
    finally:
        if owns:
            rt.shutdown()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)
    print(json.dumps(results))
    return results


if __name__ == "__main__":
    main()
