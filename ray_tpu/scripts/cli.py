"""Cluster CLI: status / list / timeline.

Reference: `python/ray/scripts/scripts.py` (`ray status`,
`ray list ...` from `ray/util/state`) — `python -m ray_tpu.scripts.cli
<cmd> --address <ready-file>`.
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str):
    import ray_tpu as rt

    rt.init(address=address)
    return rt


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # cluster-lifecycle commands run WITHOUT a live cluster (reference:
    # `ray up/down` in autoscaler/_private/commands.py)
    if argv and argv[0] in ("up", "down", "cluster-status", "attach",
                            "exec"):
        from ray_tpu.autoscaler.commands import main as cluster_main

        cmd = {"cluster-status": "status"}.get(argv[0], argv[0])
        return cluster_main([cmd] + argv[1:])
    if argv and argv[0] == "grafana-dashboard":
        # generated dashboard files, no cluster needed (reference:
        # `grafana_dashboard_factory.py`)
        gp = argparse.ArgumentParser(prog="ray_tpu grafana-dashboard")
        gp.add_argument("--out", default="grafana_dashboards")
        gargs = gp.parse_args(argv[1:])
        from ray_tpu.dashboard.grafana import write_dashboards

        for path in write_dashboards(gargs.out):
            print(path)
        return 0
    p = argparse.ArgumentParser(prog="ray_tpu")
    p.add_argument("--address", required=True,
                   help="head ready-file path (printed at init)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="cluster summary")
    lp = sub.add_parser("list", help="list cluster entities")
    lp.add_argument("what", choices=["tasks", "actors", "nodes", "jobs",
                                     "placement-groups", "workers"])
    lp.add_argument("--limit", type=int, default=100)
    ep = sub.add_parser("events", help="structured cluster event log")
    ep.add_argument("--severity", default=None)
    ep.add_argument("--limit", type=int, default=100)
    tp = sub.add_parser("timeline", help="dump chrome://tracing JSON")
    tp.add_argument("--output", default="timeline.json")
    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint")
    js.add_argument("--wait", action="store_true")
    for name in ("status", "logs", "stop"):
        jc = jsub.add_parser(name)
        jc.add_argument("job_id")
        if name == "logs":
            jc.add_argument("-f", "--follow", action="store_true",
                            help="stream logs until the job finishes")
    jsub.add_parser("list")
    args = p.parse_args(argv)

    rt = _connect(args.address)
    from ray_tpu.util import state

    try:
        if args.cmd == "status":
            print(json.dumps(state.cluster_status(), indent=2))
        elif args.cmd == "list":
            fn = {
                "tasks": lambda: state.list_tasks(limit=args.limit),
                "actors": state.list_actors,
                "nodes": state.list_nodes,
                "jobs": state.list_jobs,
                "placement-groups": state.list_placement_groups,
                "workers": state.list_workers,
            }[args.what]
            print(json.dumps(fn(), indent=2, default=str))
        elif args.cmd == "events":
            from ray_tpu.core.runtime import get_runtime

            events = get_runtime().controller_call(
                "list_cluster_events",
                {"severity": args.severity, "limit": args.limit},
            )
            print(json.dumps(events, indent=2))
        elif args.cmd == "timeline":
            events = state.timeline(args.output)
            print(f"wrote {len(events)} events to {args.output}")
        elif args.cmd == "job":
            from ray_tpu import job as job_api

            if args.job_cmd == "submit":
                jid = job_api.submit_job(args.entrypoint)
                print(jid)
                if args.wait:
                    print(job_api.wait_job(jid))
            elif args.job_cmd == "status":
                print(job_api.get_job_status(args.job_id))
            elif args.job_cmd == "logs":
                if getattr(args, "follow", False):
                    for chunk in job_api.follow_job_logs(args.job_id):
                        print(chunk, end="", flush=True)
                else:
                    print(job_api.get_job_logs(args.job_id), end="")
            elif args.job_cmd == "stop":
                print(job_api.stop_job(args.job_id))
            elif args.job_cmd == "list":
                print(json.dumps(job_api.list_jobs(), indent=2))
    finally:
        rt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
