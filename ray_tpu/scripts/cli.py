"""Cluster CLI: status / list / timeline.

Reference: `python/ray/scripts/scripts.py` (`ray status`,
`ray list ...` from `ray/util/state`) — `python -m ray_tpu.scripts.cli
<cmd> --address <ready-file>`.
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str):
    import ray_tpu as rt

    rt.init(address=address)
    return rt


def _fmt_size(n) -> str:
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n}B"


def render_memory_table(tables, kind=None, min_size=0) -> str:
    """Human rendering of `state.memory_summary()` grouped by node and
    process (reference: the `ray memory` grouped report)."""
    lines = []
    for node in tables:
        store = node.get("store") or {}
        lines.append(
            f"node {node['node_id'][:12]}  store "
            f"{_fmt_size(store.get('used'))}/"
            f"{_fmt_size(store.get('capacity'))}  "
            f"spilled {len(node.get('spilled') or [])}"
        )
        for proc in node.get("processes", []):
            refs = [
                r for r in proc.get("refs", [])
                if (kind is None or r["kind"] == kind)
                and (r.get("size") or 0) >= min_size
            ]
            lines.append(
                f"  {proc.get('mode')} pid={proc.get('pid')} "
                f"({len(refs)} refs, {proc.get('held_pins', 0)} pins)"
            )
            header = (f"    {'OBJECT':<18} {'KIND':<9} {'WHERE':<7} "
                      f"{'SIZE':>9}  L/S/B/C/T  LIN  CALLSITE")
            if refs:
                lines.append(header)
            for r in sorted(refs, key=lambda r: -(r.get("size") or 0)):
                counts = (f"{r['local']}/{r['submitted']}/"
                          f"{r['borrowers']}/{r['contained']}/"
                          f"{r['transit']}")
                lines.append(
                    f"    {r['object_id'][:16]:<18} {r['kind']:<9} "
                    f"{(r.get('where') or '-'):<7} "
                    f"{_fmt_size(r.get('size')):>9}  {counts:<9}  "
                    f"{'y' if r.get('lineage_pinned') else '-':<3}  "
                    f"{r.get('callsite') or '-'}"
                )
    return "\n".join(lines) if lines else "(no nodes)"


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    # cluster-lifecycle commands run WITHOUT a live cluster (reference:
    # `ray up/down` in autoscaler/_private/commands.py)
    if argv and argv[0] in ("up", "down", "cluster-status", "attach",
                            "exec"):
        from ray_tpu.autoscaler.commands import main as cluster_main

        cmd = {"cluster-status": "status"}.get(argv[0], argv[0])
        return cluster_main([cmd] + argv[1:])
    if argv and argv[0] == "grafana-dashboard":
        # generated dashboard files, no cluster needed (reference:
        # `grafana_dashboard_factory.py`)
        gp = argparse.ArgumentParser(prog="ray_tpu grafana-dashboard")
        gp.add_argument("--out", default="grafana_dashboards")
        gargs = gp.parse_args(argv[1:])
        from ray_tpu.dashboard.grafana import write_dashboards

        for path in write_dashboards(gargs.out):
            print(path)
        return 0
    p = argparse.ArgumentParser(prog="ray_tpu")
    p.add_argument("--address", required=True,
                   help="head ready-file path (printed at init)")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="cluster summary")
    lp = sub.add_parser("list", help="list cluster entities")
    lp.add_argument("what", choices=["tasks", "actors", "nodes", "jobs",
                                     "placement-groups", "workers"])
    lp.add_argument("--limit", type=int, default=100)
    ep = sub.add_parser("events", help="structured cluster event log")
    ep.add_argument("--severity", default=None)
    ep.add_argument("--limit", type=int, default=100)
    tp = sub.add_parser("timeline", help="dump chrome://tracing JSON")
    tp.add_argument("--output", default="timeline.json")
    mp = sub.add_parser(
        "memory",
        help="object-memory table: what is pinning the object store "
             "(reference: `ray memory`)",
    )
    mp.add_argument("--kind", choices=["owned", "borrowed", "pending"],
                    default=None)
    mp.add_argument("--min-size", type=int, default=0)
    mp.add_argument("--json", action="store_true", dest="as_json",
                    help="raw per-node tables instead of the rendering")
    jp = sub.add_parser("job", help="job submission")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint")
    js.add_argument("--wait", action="store_true")
    for name in ("status", "logs", "stop"):
        jc = jsub.add_parser(name)
        jc.add_argument("job_id")
        if name == "logs":
            jc.add_argument("-f", "--follow", action="store_true",
                            help="stream logs until the job finishes")
    jsub.add_parser("list")
    args = p.parse_args(argv)

    rt = _connect(args.address)
    from ray_tpu.util import state

    try:
        if args.cmd == "status":
            print(json.dumps(state.cluster_status(), indent=2))
        elif args.cmd == "list":
            fn = {
                "tasks": lambda: state.list_tasks(limit=args.limit),
                "actors": state.list_actors,
                "nodes": state.list_nodes,
                "jobs": state.list_jobs,
                "placement-groups": state.list_placement_groups,
                "workers": state.list_workers,
            }[args.what]
            print(json.dumps(fn(), indent=2, default=str))
        elif args.cmd == "events":
            from ray_tpu.core.runtime import get_runtime

            events = get_runtime().controller_call(
                "list_cluster_events",
                {"severity": args.severity, "limit": args.limit},
            )
            print(json.dumps(events, indent=2))
        elif args.cmd == "timeline":
            events = state.timeline(args.output)
            print(f"wrote {len(events)} events to {args.output}")
        elif args.cmd == "memory":
            if args.as_json:
                if args.kind or args.min_size:
                    # filters apply to JSON output too: flattened rows
                    out = state.list_objects(kind=args.kind,
                                             min_size=args.min_size)
                else:
                    out = state.memory_summary()
                print(json.dumps(out, indent=2, default=str))
            else:
                print(render_memory_table(
                    state.memory_summary(), kind=args.kind,
                    min_size=args.min_size,
                ))
        elif args.cmd == "job":
            from ray_tpu import job as job_api

            if args.job_cmd == "submit":
                jid = job_api.submit_job(args.entrypoint)
                print(jid)
                if args.wait:
                    print(job_api.wait_job(jid))
            elif args.job_cmd == "status":
                print(job_api.get_job_status(args.job_id))
            elif args.job_cmd == "logs":
                if getattr(args, "follow", False):
                    for chunk in job_api.follow_job_logs(args.job_id):
                        print(chunk, end="", flush=True)
                else:
                    print(job_api.get_job_logs(args.job_id), end="")
            elif args.job_cmd == "stop":
                print(job_api.stop_job(args.job_id))
            elif args.job_cmd == "list":
                print(json.dumps(job_api.list_jobs(), indent=2))
    finally:
        rt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
