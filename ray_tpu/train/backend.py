"""Backend hooks: framework-specific worker-group setup.

Reference: `train/backend.py:32` Backend(on_start/on_training_start/
on_shutdown) + per-framework configs (`train/torch/config.py:66`).
The JAX backend replaces torch.distributed rendezvous with either
host-level collective groups (default: rides the framework's own object
plane) or `jax.distributed.initialize` (multi-host SPMD, SURVEY §5.8).
"""

from __future__ import annotations

import logging
import socket
from dataclasses import dataclass, field
from typing import Dict, Optional

from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)


@dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Subclass and override hooks; all run driver-side, issuing
    `worker_group.execute` RPCs for per-worker setup."""

    def on_start(self, worker_group: WorkerGroup, backend_config: BackendConfig):
        pass

    def on_training_start(
        self, worker_group: WorkerGroup, backend_config: BackendConfig
    ):
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: BackendConfig):
        pass


# ---------------------------------------------------------------------
# JAX backend
# ---------------------------------------------------------------------
@dataclass
class JaxConfig(BackendConfig):
    """distributed_mode:
    - "collective": workers sync grads via host-level collective groups
      (`ray_tpu.parallel.collectives`); each worker runs its own local
      jax runtime over its visible chips.  Right for one-process-per-
      host-or-chip data parallelism.
    - "jax_distributed": `jax.distributed.initialize` on every worker —
      one global XLA runtime, `jax.devices()` spans all workers, pjit
      shards globally.  Right for multi-host SPMD over ICI/DCN.
    - "none": no cross-worker setup.
    """

    distributed_mode: str = "collective"
    platform: Optional[str] = None  # force JAX_PLATFORMS on workers
    env_vars: Dict[str, str] = field(default_factory=dict)
    collective_group_name: str = "train"

    @property
    def backend_cls(self):
        return JaxBackend


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _setup_worker_env(env_vars: Dict[str, str], platform: Optional[str]):
    import os

    os.environ.update(env_vars)
    # The inherited JAX_PLATFORMS env is authoritative, but plugins
    # registered by the image's sitecustomize can override jax's config;
    # re-assert through the config (same dance as tests/conftest.py).
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        try:
            import jax

            jax.config.update("jax_platforms", platform)
        except Exception as e:
            logging.getLogger(__name__).debug(
                "jax platform re-assert skipped: %s", e
            )


def _init_collective(world_size: int, rank: int, group_name: str):
    from ray_tpu.parallel import collectives

    collectives.init_collective_group(world_size, rank, group_name)


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    if num_processes > 1:
        # CPU multi-process needs gloo collectives wired into the CPU
        # client or every spanning computation dies with "Multiprocess
        # computations aren't implemented on the CPU backend".  The
        # flag must land via the config API BEFORE the backend
        # initializes — jax 0.4.x never reads it from the environment
        # (which is why env_vars alone can't fix this).  Set it
        # unconditionally: probing the selected backend here would
        # itself initialize it, and the flag only affects CPU-client
        # construction (harmless on TPU hosts).
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception as e:
            # older/newer flag surface: let initialize() proceed and
            # surface the real capability error, if any
            import logging

            logging.getLogger(__name__).debug(
                "cpu gloo collectives flag unavailable: %s", e
            )
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        worker_group.execute(
            _setup_worker_env, backend_config.env_vars, backend_config.platform
        )

    def on_training_start(
        self, worker_group: WorkerGroup, backend_config: JaxConfig
    ):
        n = len(worker_group)
        mode = backend_config.distributed_mode
        if n <= 1 or mode == "none":
            return
        if mode == "collective":
            import ray_tpu as rt

            # rank 0 first: it hosts the rendezvous actor others look up
            rt.get(worker_group.workers[0].execute.remote(
                _init_collective, n, 0, backend_config.collective_group_name
            ))
            rt.get([
                w.execute.remote(
                    _init_collective, n, i, backend_config.collective_group_name
                )
                for i, w in enumerate(worker_group.workers)
                if i > 0
            ])
        elif mode == "jax_distributed":
            # pick host AND port on worker 0 — the coordinator binds
            # there, so a driver-side free port would be wrong
            host, port = worker_group.execute_single(0, _coordinator_addr)
            coordinator = f"{host}:{port}"
            import ray_tpu as rt

            rt.get([
                w.execute.remote(_init_jax_distributed, coordinator, n, i)
                for i, w in enumerate(worker_group.workers)
            ])
        else:
            raise ValueError(f"unknown distributed_mode: {mode}")

    def on_shutdown(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        # Kill the rendezvous actor driver-side: worker 0 may already be
        # dead (FailureConfig restart path), and the named actor must not
        # survive into the next attempt or rank 0's re-registration
        # collides with the stale name.
        if len(worker_group) > 1 and backend_config.distributed_mode == "collective":
            import ray_tpu as rt

            name = f"__rt_collective__{backend_config.collective_group_name}"
            try:
                rt.kill(rt.get_actor(name))
            except Exception as e:
                # best-effort: the rendezvous actor may never have been
                # created (group died before on_training_start)
                logger.debug("rendezvous actor cleanup: %s", e)


def _coordinator_addr():
    host = socket.gethostbyname(socket.gethostname())
    return host, _free_port()
