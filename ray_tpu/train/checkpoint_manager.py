"""Checkpoint retention + atomic commit.

Top-K retention follows the reference
(`train/_internal/checkpoint_manager.py`).  The commit path is the
elastic-training primitive on top: a checkpoint becomes "latest" only
via an atomic rename of a fully-staged directory carrying a per-file
checksum manifest, so a worker preempted mid-save (or a driver killed
mid-copy) can never leave a half-written directory the restore path
will trust.  `validate_checkpoint` re-verifies the manifest on restore
and the trainer's recovery path walks `latest_valid` — corrupted or
partial checkpoints are skipped, not loaded.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import uuid
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train.checkpoint import (
    Checkpoint,
    _new_checkpoint_dirname,
    merge_into,
)
from ray_tpu.train.config import CheckpointConfig

logger = logging.getLogger(__name__)

_COMMIT_MANIFEST = "commit_manifest.json"
_STAGING_PREFIX = ".tmp_checkpoint_"
_RETIRED_PREFIX = ".retired_checkpoint_"


class CheckpointCommitError(RuntimeError):
    """The staged checkpoint would not pass its own restore
    validation (e.g. a partial round merged fewer writer ranks than
    the sharded manifest promises) — it was NOT published and the
    previous checkpoint remains `latest`."""


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _walk_files(dir_: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(dir_):
        for fn in files:
            out.append(os.path.relpath(os.path.join(root, fn), dir_))
    return sorted(out)


def write_commit_manifest(dir_: str, index: int) -> None:
    """Record every staged file's size + crc32, fsync'd, as the last
    write before the publishing rename."""
    files: Dict[str, Dict[str, Optional[int]]] = {}
    for rel in _walk_files(dir_):
        if rel == _COMMIT_MANIFEST:
            continue
        p = os.path.join(dir_, rel)
        # piece archives whose sharded index records per-piece crc32s
        # are covered byte-for-byte by load_sharded's read-time
        # verification: recording crc32=None skips re-reading multi-GB
        # params on EVERY per-step commit (size is still recorded and
        # checked; every other file gets the full CRC)
        if (os.path.basename(rel).startswith("pieces_r")
                and rel.endswith(".npz")
                and _piece_crcs_recorded(dir_, rel)):
            crc: Optional[int] = None
        else:
            crc = _file_crc32(p)
        files[rel] = {"size": os.path.getsize(p), "crc32": crc}
    manifest = {"version": 1, "index": index, "files": files}
    path = os.path.join(dir_, _COMMIT_MANIFEST)
    with open(path, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())


def _piece_crcs_recorded(dir_: str, npz_rel: str) -> bool:
    """True when the sharded index alongside `pieces_rNNNNN.npz`
    records a per-piece crc32 for every piece — i.e. `load_sharded`
    will itself verify these bytes at read time."""
    idx = os.path.join(dir_, npz_rel[:-len(".npz")] + ".json")
    try:
        with open(idx) as f:
            entries = json.load(f)
    except (OSError, ValueError):
        return False
    return bool(entries) and all(
        e.get("crc32") is not None for e in entries
    )


def validate_checkpoint(path: str, fast: bool = False) -> Tuple[bool, str]:
    """Is `path` a complete, uncorrupted committed checkpoint?

    - no commit manifest → LEGACY-valid (user-supplied
      `resume_from_checkpoint` directories predate the commit
      protocol) as long as the directory exists and is non-empty;
    - with a manifest, every listed file must exist with matching size
      and crc32;
    - a sharded checkpoint must additionally carry the piece index of
      EVERY writer rank its own manifest promises — a merge that lost
      a rank's pieces assembles garbage and is rejected here instead
      of at `load_sharded`'s partial-coverage error deep in the loop.

    With ``fast=True`` (the restore hot path), piece archives whose
    sharded index records per-piece checksums skip the whole-file CRC
    — `load_sharded` verifies exactly those bytes at read time, so the
    recovery window reads multi-GB params once, not twice.  Existence
    and size are always checked; all other files always get the full
    CRC."""
    if not os.path.isdir(path):
        return False, "not a directory"
    mpath = os.path.join(path, _COMMIT_MANIFEST)
    if not os.path.exists(mpath):
        if not os.listdir(path):
            return False, "empty checkpoint directory"
        return True, "legacy (no commit manifest)"
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable commit manifest: {e}"
    for rel, meta in manifest.get("files", {}).items():
        p = os.path.join(path, rel)
        if not os.path.exists(p):
            return False, f"missing file {rel}"
        if os.path.getsize(p) != meta.get("size"):
            return False, f"size mismatch for {rel}"
        if meta.get("crc32") is None:
            # recorded as piece-CRC-covered at commit time: integrity
            # of these bytes is verified by load_sharded on read
            continue
        if (fast and os.path.basename(rel).startswith("pieces_r")
                and rel.endswith(".npz")
                and _piece_crcs_recorded(path, rel)):
            continue
        try:
            if _file_crc32(p) != meta.get("crc32"):
                return False, f"checksum mismatch for {rel}"
        except OSError as e:
            return False, f"unreadable file {rel}: {e}"
    sharded = os.path.join(path, "sharded_manifest.json")
    if os.path.exists(sharded):
        try:
            with open(sharded) as f:
                n = int(json.load(f).get("num_processes", 1))
        except (OSError, ValueError) as e:
            return False, f"unreadable sharded manifest: {e}"
        for r in range(n):
            if not os.path.exists(
                os.path.join(path, f"pieces_r{r:05d}.json")
            ):
                return False, f"missing sharded pieces for rank {r}/{n}"
    return True, "ok"


def sweep_staging(run_dir: str) -> int:
    """Remove orphaned staging/retired directories (a driver killed
    mid-commit leaves `.tmp_checkpoint_*` / `.retired_checkpoint_*`
    behind; neither is ever a published checkpoint and they must not
    accumulate).  Returns the number swept."""
    n = 0
    try:
        entries = os.listdir(run_dir)
    except OSError:
        return 0
    for entry in entries:
        if entry.startswith((_STAGING_PREFIX, _RETIRED_PREFIX)):
            shutil.rmtree(os.path.join(run_dir, entry), ignore_errors=True)
            n += 1
    return n


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []

    def commit(
        self,
        reported: List[Checkpoint],
        run_dir: str,
        index: int,
        metrics: Dict[str, Any],
    ) -> Checkpoint:
        """Atomic publish of one training iteration's checkpoint: merge
        every reporting rank into a staging directory, stamp metadata,
        write the per-file checksum manifest (fsync'd), then rename
        into place.  Readers either see the previous checkpoint or the
        complete new one — never a partial merge."""
        staging = os.path.join(
            run_dir, f"{_STAGING_PREFIX}{index:06d}_{uuid.uuid4().hex[:8]}"
        )
        final = os.path.join(run_dir, _new_checkpoint_dirname(index))
        retired = None
        try:
            for ck in reported:
                merge_into(ck, staging)
            staged = Checkpoint(staging)
            staged.update_metadata({"iteration": index})
            write_commit_manifest(staging, index)
            ok, why = validate_checkpoint(staging, fast=True)
            if not ok:
                # a commit that its own restore validation rejects
                # (e.g. a stop-boundary round merged fewer writer
                # ranks than the sharded manifest promises) must
                # never be published — and must never trigger the
                # retention sweep that could evict the last GOOD one
                raise CheckpointCommitError(why)
            if os.path.exists(final):
                # an index collision (a run_dir reused across fit()
                # calls) supersedes the old commit — but the old data
                # must never be DESTROYED before the replacement is
                # published: rename it aside (crash-safe), reap after
                retired = os.path.join(
                    run_dir,
                    f"{_RETIRED_PREFIX}{index:06d}_{uuid.uuid4().hex[:8]}",
                )
                os.rename(final, retired)
            os.rename(staging, final)
        except BaseException:
            shutil.rmtree(staging, ignore_errors=True)
            if retired is not None and not os.path.exists(final):
                # publishing failed after the aside-rename: put the
                # old commit back so "latest" still exists on disk
                try:
                    os.rename(retired, final)
                    retired = None
                except OSError as e:
                    logger.warning(
                        "could not restore retired checkpoint %s: %s",
                        retired, e,
                    )
            raise
        if retired is not None:
            shutil.rmtree(retired, ignore_errors=True)
        _fsync_dir(run_dir)
        persisted = Checkpoint(final)
        self.register(persisted, metrics, index)
        return persisted

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any],
                 index: int) -> None:
        self._checkpoints = [
            c for c in self._checkpoints if c.checkpoint != checkpoint
        ]
        self._checkpoints.append(_TrackedCheckpoint(checkpoint, metrics, index))
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            evict = self._checkpoints.pop(0)  # oldest
        else:
            sign = 1 if self.config.checkpoint_score_order == "max" else -1
            worst = min(
                (c for c in self._checkpoints[:-1]),  # never evict the newest
                key=lambda c: sign * float(c.metrics.get(attr, float("-inf") * sign)),
                default=None,
            )
            if worst is None:
                return
            self._checkpoints.remove(worst)
            evict = worst
        shutil.rmtree(evict.checkpoint.path, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda c: c.index).checkpoint

    @property
    def latest_valid(self) -> Optional[Checkpoint]:
        """Newest tracked checkpoint that passes commit-manifest
        validation — the elastic restore entry point.  Corrupted or
        partial directories are logged and skipped, never loaded."""
        for tracked in sorted(
            self._checkpoints, key=lambda c: c.index, reverse=True
        ):
            path = tracked.checkpoint.path
            # fast=True: piece files (the multi-GB bulk) skip the
            # whole-file CRC here because load_sharded verifies their
            # per-piece checksums at read time anyway — the restore
            # window pays one read of the bytes, not two
            ok, why = validate_checkpoint(path, fast=True)
            if ok:
                return tracked.checkpoint
            logger.warning(
                "skipping checkpoint %s for restore: %s", path, why,
            )
        return None

    @property
    def best(self) -> Optional[Checkpoint]:
        attr = self.config.checkpoint_score_attribute
        if not self._checkpoints:
            return None
        if attr is None:
            return self.latest
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        return max(
            self._checkpoints,
            key=lambda c: sign * float(c.metrics.get(attr, float("-inf") * sign)),
        ).checkpoint

    @property
    def best_checkpoints(self) -> List[tuple]:
        return [(c.checkpoint, c.metrics) for c in self._checkpoints]


def _fsync_dir(path: str) -> None:
    """Durably record a rename in its parent directory (best-effort on
    filesystems without directory fsync)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError as e:
        logger.debug("cannot fsync dir %s: %s", path, e)
        return
    try:
        os.fsync(fd)
    except OSError as e:
        logger.debug("dir fsync failed for %s: %s", path, e)
    finally:
        os.close(fd)
