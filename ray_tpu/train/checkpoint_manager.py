"""Top-K checkpoint retention (reference:
`train/_internal/checkpoint_manager.py`)."""

from __future__ import annotations

import shutil
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import CheckpointConfig


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    metrics: Dict[str, Any]
    index: int


class CheckpointManager:
    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._checkpoints: List[_TrackedCheckpoint] = []

    def register(self, checkpoint: Checkpoint, metrics: Dict[str, Any],
                 index: int) -> None:
        self._checkpoints.append(_TrackedCheckpoint(checkpoint, metrics, index))
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        attr = self.config.checkpoint_score_attribute
        if attr is None:
            evict = self._checkpoints.pop(0)  # oldest
        else:
            sign = 1 if self.config.checkpoint_score_order == "max" else -1
            worst = min(
                (c for c in self._checkpoints[:-1]),  # never evict the newest
                key=lambda c: sign * float(c.metrics.get(attr, float("-inf") * sign)),
                default=None,
            )
            if worst is None:
                return
            self._checkpoints.remove(worst)
            evict = worst
        shutil.rmtree(evict.checkpoint.path, ignore_errors=True)

    @property
    def latest(self) -> Optional[Checkpoint]:
        if not self._checkpoints:
            return None
        return max(self._checkpoints, key=lambda c: c.index).checkpoint

    @property
    def best(self) -> Optional[Checkpoint]:
        attr = self.config.checkpoint_score_attribute
        if not self._checkpoints:
            return None
        if attr is None:
            return self.latest
        sign = 1 if self.config.checkpoint_score_order == "max" else -1
        return max(
            self._checkpoints,
            key=lambda c: sign * float(c.metrics.get(attr, float("-inf") * sign)),
        ).checkpoint

    @property
    def best_checkpoints(self) -> List[tuple]:
        return [(c.checkpoint, c.metrics) for c in self._checkpoints]
