"""JAX helpers for the train loop.

Reference analog: `train/torch/train_loop_utils.py` (prepare_model /
prepare_data_loader wrap DDP).  Here the cross-worker primitive is
`sync_gradients`: host-level allreduce of a gradient pytree over the
worker collective group.  Within one worker, parallelism is in-program
(pjit over the worker's mesh) — the TPU-native fast path; use this
host path only to bridge separate JAX runtimes (one per worker).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _flatten_to_vector(tree):
    import jax

    leaves, treedef = jax.tree.flatten(tree)
    arrs = [np.asarray(l).ravel() for l in leaves]
    shapes = [np.asarray(l).shape for l in leaves]
    vec = np.concatenate(arrs) if arrs else np.zeros(0, np.float32)
    return vec, (treedef, shapes, [a.dtype for a in arrs])


def _unflatten_from_vector(vec, meta):
    import jax

    treedef, shapes, dtypes = meta
    out, off = [], 0
    for shape, dt in zip(shapes, dtypes):
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out.append(vec[off : off + n].astype(dt).reshape(shape))
        off += n
    return jax.tree.unflatten(treedef, out)


def sync_gradients(grads: Any, group_name: str = "train"):
    """Mean-allreduce a gradient pytree across the worker group.

    Single flattened exchange (not per-leaf) so one rendezvous round
    carries the whole gradient. No-op when no collective group exists
    (single-worker runs work unchanged).
    """
    from ray_tpu.parallel import collectives

    try:
        group = collectives.get_group(group_name)
    except KeyError:
        return grads
    vec, meta = _flatten_to_vector(grads)
    reduced = group.allreduce(vec, op="mean")
    return _unflatten_from_vector(reduced, meta)


def world_mean(value: float, group_name: str = "train") -> float:
    from ray_tpu.parallel import collectives

    try:
        group = collectives.get_group(group_name)
    except KeyError:
        return float(value)
    return float(group.allreduce(np.asarray([value], np.float64), op="mean")[0])


def prepare_batch(batch, mesh=None, sharding=None):
    """device_put a host batch with data sharding over the mesh."""
    import jax

    if sharding is None and mesh is not None:
        from ray_tpu.parallel import data_sharding

        sharding = data_sharding(mesh)
    if sharding is None:
        return jax.device_put(batch)
    return jax.device_put(batch, sharding)
