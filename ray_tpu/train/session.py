"""Per-worker training session.

Reference: `train/_internal/session.py` — the user's train loop runs in
a session thread inside each worker actor; `report()` hands
(metrics, checkpoint) to the actor's result queue, which the
BackendExecutor polls.  `get_context()` exposes rank/world info
(reference `train/context.py:26`); TPU-native addition: `get_mesh()`
builds the worker's device mesh from the ScalingConfig.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class _TrainingResult:
    """One unit handed from session thread -> actor -> executor."""

    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    done: bool = False
    error: Optional[BaseException] = None


@dataclass
class TrainContext:
    """Reference: `train/context.py` TrainContext."""

    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    local_world_size: int = 1
    experiment_name: str = ""
    trial_name: str = ""
    trial_id: str = ""
    mesh_shape: Optional[Dict[str, int]] = None
    storage_path: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_local_world_size(self) -> int:
        return self.local_world_size

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name

    def get_trial_id(self) -> str:
        return self.trial_id

    def get_target_world_size(self) -> int:
        """The ScalingConfig's requested width.  Under an elastic
        shrink (`FailureConfig(elastic=True)`) `get_world_size()` may
        be smaller; the difference tells the loop it is running
        degraded and will be re-grown when capacity returns."""
        return int(self.extra.get("target_world_size", self.world_size))

    def is_elastic(self) -> bool:
        return bool(self.extra.get("elastic", False))

    def get_mesh(self):
        """Build this worker's jax mesh per the ScalingConfig's
        ``mesh_shape`` (all local devices if unset).

        Elastic runs re-form at a smaller width, so the requested
        shape may no longer match the visible device count — then the
        spec is re-fit via `MeshSpec.fit_to`: model axes preserved,
        data axes (dp first) shrunk to cover the surviving devices."""
        import jax

        from ray_tpu.parallel import MeshSpec

        shape = dict(self.mesh_shape or {})
        n = shape.pop("n", None)
        devices = jax.devices()[: n or len(jax.devices())]
        spec = MeshSpec(**shape)
        try:
            return spec.build(devices)
        except ValueError:
            if not self.is_elastic():
                raise
            return spec.fit_to(len(devices)).build(devices)


class _Session:
    """Holds the queue between the user loop thread and the actor."""

    def __init__(
        self,
        context: TrainContext,
        checkpoint: Optional[Checkpoint],
        datasets: Optional[Dict[str, Any]] = None,
    ):
        self.context = context
        self.result_queue: "queue.Queue[_TrainingResult]" = queue.Queue(maxsize=1)
        self.loaded_checkpoint = checkpoint
        self.datasets = datasets or {}
        # stop: unwind at the NEXT step barrier, after delivering the
        # current result (graceful — the executor keeps consuming).
        # abandoned: the executor has stopped consuming (elastic drain,
        # teardown); skip delivery entirely so nothing blocks on the
        # 1-deep queue.
        self.stop_requested = threading.Event()
        self.abandoned = threading.Event()
        self.iteration = 0

    def report(self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint]):
        self.iteration += 1
        # An ABANDONED session's results have no consumer: skip the put
        # (it could block forever on the 1-deep queue) and unwind at
        # the step barrier now.  A graceful stop still DELIVERS this
        # round — dropping it would hand the trainer a partial round
        # and a partial (invalid) checkpoint commit.
        if self.abandoned.is_set():
            raise StopIteration("training session abandoned")
        # Blocks when the executor is behind — natural backpressure, the
        # same semantics as the reference's result queue.
        self.result_queue.put(_TrainingResult(metrics=metrics, checkpoint=checkpoint))
        if self.stop_requested.is_set():
            raise StopIteration("training stop requested")


_session_local = threading.local()


def _set_session(s: Optional[_Session]):
    _session_local.value = s


def _get_session() -> Optional[_Session]:
    return getattr(_session_local, "value", None)


# ---------------------------------------------------------------------
# public in-loop API (reference: `train/_internal/session.py:403,667,754`)
# ---------------------------------------------------------------------
def report(metrics: Dict[str, Any], *, checkpoint: Optional[Checkpoint] = None):
    s = _get_session()
    if s is None:
        raise RuntimeError(
            "train.report() called outside a training session"
        )
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _get_session()
    if s is None:
        raise RuntimeError("get_checkpoint() called outside a training session")
    return s.loaded_checkpoint


def get_context() -> TrainContext:
    s = _get_session()
    if s is None:
        # Outside a session: a degenerate single-worker context, so the
        # same train loop runs standalone (reference behaves likewise).
        return TrainContext()
    return s.context


def get_dataset_shard(name: str = "train"):
    s = _get_session()
    if s is None:
        raise RuntimeError("get_dataset_shard() called outside a training session")
    return s.datasets.get(name)
