"""Sharded (multi-process) checkpointing with reshard-on-restore.

The flagship-FT primitive SURVEY §7 demands: "worker loss => new mesh
=> recompile + reshard from checkpoint — reshard-on-resume must be
native".  Reference analog: Ray Train persists per-rank checkpoint
files through `train/_internal/storage.py`; torch-XLA consolidates
shards host-side.  TPU-native design instead:

- **save**: every jax process writes ONLY its addressable shards (no
  host gather, no cross-process traffic) into its own piece file, with
  the global slice each piece covers recorded alongside.  Replicated
  shards are written once (``replica_id == 0``).
- **restore**: each target device shard is assembled from the saved
  pieces that overlap it via `jax.make_array_from_callback` — so a
  checkpoint written under mesh A loads under ANY mesh B with the same
  global shapes, reading only the bytes each process needs.

The piece files from different ranks merge into one checkpoint
directory (ray_tpu.train's `persist_checkpoint` already merges all
reporting ranks); on multi-host deployments the run storage_path must
be a shared filesystem, exactly as the reference requires for
`storage_path`.
"""

from __future__ import annotations

import json
import os
import pickle
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_MANIFEST = "sharded_manifest.json"
_AUX = "sharded_aux.pkl"


def _leaf_key(path) -> str:
    import jax

    return jax.tree_util.keystr(path)


def _is_jax_array(x) -> bool:
    import jax

    return isinstance(x, jax.Array)


def save_sharded(tree: Any, dir_: str) -> None:
    """Write this process's shards of every jax.Array leaf in `tree`
    under `dir_`.  Non-array leaves (step counters, rng keys as numpy,
    plain scalars) are written by process 0 only.  Every participating
    process must call this (each writes distinct files; no barrier is
    taken — the caller's report/collect cycle is the barrier)."""
    import jax

    os.makedirs(dir_, exist_ok=True)
    pid = jax.process_index()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    pieces: Dict[str, np.ndarray] = {}
    index: List[Dict[str, Any]] = []
    # num_processes lets the loader ignore stale rank files left in a
    # reused directory by an earlier, larger-world save (no barrier to
    # clean them here without racing concurrent writers)
    manifest: Dict[str, Any] = {
        "version": 1, "leaves": {}, "num_processes": jax.process_count(),
    }
    aux: Dict[str, Any] = {}
    n = 0
    for path, leaf in leaves:
        key = _leaf_key(path)
        if _is_jax_array(leaf):
            manifest["leaves"][key] = {
                "shape": list(leaf.shape),
                "dtype": str(leaf.dtype),
            }
            for shard in leaf.addressable_shards:
                if shard.replica_id != 0:
                    continue  # replicated copy: some other shard writes it
                data = np.asarray(shard.data)
                piece_key = f"p{n}"
                n += 1
                pieces[piece_key] = data
                index.append({
                    "key": piece_key,
                    "leaf": key,
                    "start": [
                        (sl.start or 0) for sl in shard.index
                    ] if shard.index else [0] * data.ndim,
                    "shape": list(data.shape),
                    # per-piece checksum over the raw buffer: restore
                    # verifies it so a piece corrupted between commit
                    # and restore (truncated copy, bit rot) fails loud
                    # instead of silently assembling garbage params
                    "crc32": zlib.crc32(np.ascontiguousarray(data).tobytes()),
                })
        else:
            aux[key] = leaf
    if pieces:
        np.savez(os.path.join(dir_, f"pieces_r{pid:05d}.npz"), **pieces)
    with open(os.path.join(dir_, f"pieces_r{pid:05d}.json"), "w") as f:
        json.dump(index, f)
    if pid == 0:
        with open(os.path.join(dir_, _AUX), "wb") as f:
            pickle.dump(aux, f)
        with open(os.path.join(dir_, _MANIFEST), "w") as f:
            json.dump(manifest, f)


def _overlap(dst_sl: Tuple[slice, ...], start: List[int],
             shape: List[int]):
    """Intersection of a piece [start, start+shape) with a requested
    global region; returns (dst_local, src_local) slice tuples or None."""
    dst_local, src_local = [], []
    for d, (sl, p0, plen) in enumerate(zip(dst_sl, start, shape)):
        r0 = sl.start or 0
        r1 = sl.stop
        lo = max(r0, p0)
        hi = min(r1, p0 + plen)
        if lo >= hi:
            return None
        dst_local.append(slice(lo - r0, hi - r0))
        src_local.append(slice(lo - p0, hi - p0))
    return tuple(dst_local), tuple(src_local)


class _PieceReader:
    def __init__(self, dir_: str, num_processes: Optional[int] = None):
        self._dir = dir_
        self._npz: Dict[str, Any] = {}
        self._verified: set = set()
        # leaf key -> [(rank_file, piece_key, start, shape, crc32|None)]
        self.by_leaf: Dict[str, List] = {}
        self.ranks_seen: set = set()
        for fn in sorted(os.listdir(dir_)):
            if fn.startswith("pieces_r"):
                rank = int(fn[len("pieces_r"):].split(".")[0])
                if num_processes is not None and rank >= num_processes:
                    continue  # stale file from an earlier larger save
            if fn.startswith("pieces_r") and fn.endswith(".json"):
                self.ranks_seen.add(rank)
                with open(os.path.join(dir_, fn)) as f:
                    for ent in json.load(f):
                        self.by_leaf.setdefault(ent["leaf"], []).append(
                            (fn[:-5] + ".npz", ent["key"],
                             ent["start"], ent["shape"],
                             ent.get("crc32"))
                        )

    def read(self, npz_name: str, key: str,
             crc: Optional[int] = None) -> np.ndarray:
        z = self._npz.get(npz_name)
        if z is None:
            z = self._npz[npz_name] = np.load(
                os.path.join(self._dir, npz_name)
            )
        arr = z[key]
        if crc is not None and (npz_name, key) not in self._verified:
            got = zlib.crc32(np.ascontiguousarray(arr).tobytes())
            if got != crc:
                raise ValueError(
                    f"checkpoint piece {npz_name}:{key} is corrupted "
                    f"(crc32 {got:#x} != recorded {crc:#x})"
                )
            self._verified.add((npz_name, key))
        return arr

    def assemble(self, leaf: str, region: Tuple[slice, ...],
                 shape, dtype) -> np.ndarray:
        """Build the requested global region of `leaf` from overlapping
        pieces (the reshard-on-restore core: pieces written by any
        N-process layout assemble into any M-process target layout)."""
        full = tuple(
            slice(sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(region, shape)
        )
        out_shape = tuple(sl.stop - sl.start for sl in full)
        out = np.empty(out_shape, dtype=dtype)
        covered = 0
        for npz_name, key, start, pshape, crc in self.by_leaf.get(leaf, ()):
            ov = _overlap(full, start, pshape)
            if ov is None:
                continue
            dst, src = ov
            out[dst] = self.read(npz_name, key, crc)[src]
            covered += int(np.prod([s.stop - s.start for s in dst]))
        want = int(np.prod(out_shape))
        if covered < want:
            raise ValueError(
                f"checkpoint pieces cover {covered}/{want} elements of "
                f"{leaf}{full} — incomplete checkpoint directory?"
            )
        return out


def load_sharded(dir_: str, target: Any) -> Any:
    """Restore a tree saved by `save_sharded` onto `target`'s shardings.

    `target` is a pytree matching the saved structure whose jax.Array
    leaves carry the DESIRED sharding (freshly-initialized state on the
    new mesh, or `jax.ShapeDtypeStruct`s with `.sharding` set).  Each
    process reads only the pieces overlapping its addressable shards —
    resharding between save and load meshes is implicit."""
    import jax

    if not os.path.exists(os.path.join(dir_, _MANIFEST)):
        raise FileNotFoundError(f"no sharded checkpoint in {dir_}")
    with open(os.path.join(dir_, _MANIFEST)) as f:
        manifest = json.load(f)
    aux: Dict[str, Any] = {}
    if os.path.exists(os.path.join(dir_, _AUX)):
        from ray_tpu.core import serialization

        with open(os.path.join(dir_, _AUX), "rb") as f:
            aux = serialization.loads(f.read())
    reader = _PieceReader(dir_, manifest.get("num_processes"))
    want_ranks = manifest.get("num_processes")
    if want_ranks is not None:
        missing = set(range(int(want_ranks))) - reader.ranks_seen
        if missing:
            # the save was made by N writers but the merged directory
            # lost some of them (a preempted rank never reported, a
            # partial copy): refuse up front rather than failing on
            # partial coverage mid-assembly — or worse, assembling a
            # replicated leaf from the wrong rank's stale piece
            raise ValueError(
                f"incomplete sharded checkpoint {dir_}: missing piece "
                f"files for rank(s) {sorted(missing)} of {want_ranks}"
            )

    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(target)
    out = []
    for path, leaf in paths_leaves:
        key = _leaf_key(path)
        meta = manifest["leaves"].get(key)
        if meta is None:
            if key in aux:
                out.append(aux[key])
                continue
            raise KeyError(f"{key} not present in checkpoint {dir_}")
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        if tuple(getattr(leaf, "shape", shape)) != shape:
            raise ValueError(
                f"{key}: target shape {tuple(leaf.shape)} != saved {shape}"
            )
        sharding = getattr(leaf, "sharding", None)
        if sharding is None:
            out.append(reader.assemble(
                key, tuple(slice(0, s) for s in shape), shape, dtype
            ))
            continue
        arr = jax.make_array_from_callback(
            shape, sharding,
            lambda idx, _k=key, _s=shape, _d=dtype: reader.assemble(
                _k, idx, _s, _d
            ),
        )
        out.append(arr)
    return jax.tree_util.tree_unflatten(treedef, out)
