"""ray_tpu.train — distributed training orchestration.

Reference surface: `ray.train` (SURVEY §2.4 Ray Train) — trainers,
worker groups, in-loop session API, checkpoints, failure handling —
rebuilt JAX/TPU-first (JaxBackend replaces the torch.distributed
backend; meshes come from the ScalingConfig).
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxBackend, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    ElasticWorkerLost,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import (
    CheckpointManager,
    validate_checkpoint,
)
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.result import Result
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    report,
)
from ray_tpu.train.sharded_checkpoint import load_sharded, save_sharded
from ray_tpu.train.torch import TorchConfig, TorchTrainer
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
    TrainingFailedError,
)
from ray_tpu.train.worker_group import WorkerGroup

__all__ = [
    "Backend",
    "BackendConfig",
    "BackendExecutor",
    "BaseTrainer",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "ElasticWorkerLost",
    "FailureConfig",
    "JaxBackend",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TorchConfig",
    "TorchTrainer",
    "TrainContext",
    "TrainingFailedError",
    "TrainingWorkerError",
    "WorkerGroup",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "load_sharded",
    "report",
    "save_sharded",
    "validate_checkpoint",
]
