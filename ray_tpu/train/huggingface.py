"""HuggingFace Transformers integration for TorchTrainer.

Reference: `python/ray/train/huggingface/transformers/` — the modern
shape (`_transformers_utils.py`): the user builds a normal
`transformers.Trainer` inside `train_loop_per_worker`, calls
:func:`prepare_trainer` on it, and adds :class:`RayTrainReportCallback`;
training then runs under the framework's distributed worker group
(torch gloo here) with metrics/checkpoints flowing through
`train.report`.

    def train_loop(config):
        trainer = transformers.Trainer(model, args, ...)
        trainer.add_callback(RayTrainReportCallback())
        trainer = prepare_trainer(trainer)
        trainer.train()

    TorchTrainer(train_loop, scaling_config=ScalingConfig(num_workers=2)).fit()
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional

from ray_tpu.train import session as _session
from ray_tpu.train.checkpoint import Checkpoint


class RayTrainReportCallback:
    """`transformers.TrainerCallback` that forwards HF log/save events
    into `train.report` (reference: `RayTrainReportCallback` in
    `train/huggingface/transformers/_transformers_utils.py`).

    Implemented duck-typed (the callback protocol is plain methods), so
    importing this module never requires transformers.
    """

    def __init__(self):
        self._latest_metrics: Dict[str, Any] = {}

    # -- transformers.TrainerCallback protocol (subset) ----------------
    def on_log(self, args, state, control, logs=None, **kwargs):
        if logs:
            self._latest_metrics.update(logs)
            self._latest_metrics["step"] = state.global_step
            self._latest_metrics["epoch"] = state.epoch

    def on_save(self, args, state, control, **kwargs):
        # the checkpoint HF just wrote becomes a framework Checkpoint
        ckpt_dir = os.path.join(
            args.output_dir, f"checkpoint-{state.global_step}"
        )
        checkpoint = (
            Checkpoint.from_directory(ckpt_dir)
            if os.path.isdir(ckpt_dir) else None
        )
        _session.report(dict(self._latest_metrics), checkpoint=checkpoint)

    def on_train_end(self, args, state, control, **kwargs):
        if self._latest_metrics:
            _session.report(dict(self._latest_metrics))

    # unused protocol hooks -------------------------------------------
    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a, **k: None
        raise AttributeError(name)


def prepare_trainer(trainer):
    """Adapt a `transformers.Trainer` to the distributed worker group:
    pin no-cuda/world-size args to the session's environment and make
    sure a report callback is attached (reference: `prepare_trainer`).
    """
    ctx = _session.get_context()
    args = trainer.args
    # The gloo worker group is CPU; HF resolved device placement when
    # the Trainer was CONSTRUCTED, so flipping use_cpu alone is too
    # late — force the resolved device count to zero and move the
    # model back, or two workers would contend for cuda:0
    if hasattr(args, "use_cpu"):
        args.use_cpu = True
    if hasattr(args, "_n_gpu"):
        args._n_gpu = 0
    model = getattr(trainer, "model", None)
    if model is not None and hasattr(model, "to"):
        try:
            trainer.model = model.to("cpu")
        except Exception:
            pass
    # HF reads the torch.distributed env set up by our backend; make
    # sure per-worker output dirs don't collide — neither across ranks
    # on shared filesystems nor across concurrent runs on one machine
    if ctx.world_size > 1 and ctx.world_rank != 0:
        args.output_dir = tempfile.mkdtemp(
            prefix=f"hf_worker_{ctx.world_rank}_"
        )
    handler = getattr(trainer, "callback_handler", None)
    has_report = handler is not None and any(
        isinstance(cb, RayTrainReportCallback) for cb in handler.callbacks
    )
    if not has_report:
        trainer.add_callback(RayTrainReportCallback())
    return trainer
