"""Trainers: fit() orchestration over the BackendExecutor.

Reference: `train/base_trainer.py:111` BaseTrainer.fit,
`train/data_parallel_trainer.py:25` DataParallelTrainer.  Differences
by design: fit() drives the executor directly (the reference detours
through Tune — our Tune-equivalent wraps trainers via
`as_trainable()` the same way, see `ray_tpu/tune`).
"""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.core.retry import backoff_delay_s
from ray_tpu.metrics import metric_defs as _mdefs
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    ElasticWorkerLost,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.checkpoint_manager import (
    CheckpointCommitError,
    CheckpointManager,
    sweep_staging,
)
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result

logger = logging.getLogger(__name__)


class TrainingFailedError(RuntimeError):
    """Raised by fit() when training fails beyond FailureConfig limits."""


def _is_capacity_error(e: BaseException) -> bool:
    """Start failures worth waiting out: the cluster momentarily lacks
    the bundles/workers (preempted capacity routinely returns), as
    opposed to deterministic config/backend failures."""
    msg = str(e)
    return any(s in msg for s in (
        "could not reserve",
        "no node can host actor",
        "resources no longer available",
        "no idle worker",
    ))


class BaseTrainer:
    # per-iteration hook (metrics, persisted_checkpoint|None) used by the
    # Tune integration to forward reports to the trial (reference: the
    # trainable wrapper re-reporting, base_trainer.py:819)
    _result_callback = None

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap into a Tune Trainable (reference `base_trainer.py:819`);
        imported lazily to keep train usable without tune."""
        from ray_tpu.tune.trainable import wrap_trainer

        return wrap_trainer(self)


class DataParallelTrainer(BaseTrainer):
    """SPMD training: the same train_loop_per_worker on N workers.

    Reference: `train/data_parallel_trainer.py:25,428`.  The loop calls
    `ray_tpu.train.report(metrics, checkpoint=...)` each iteration; rank
    0's metrics become the run's reported metrics.
    """

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or self._default_backend_config

    # -- storage layout ------------------------------------------------
    def _run_dir(self) -> str:
        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        d = os.path.join(self.run_config.storage_path, name)
        os.makedirs(d, exist_ok=True)
        return d

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        for k, v in stop.items():
            if k == "training_iteration":
                if metrics.get("training_iteration", 0) >= v:
                    return True
            elif k in metrics and metrics[k] >= v:
                return True
        return False

    def fit(self) -> Result:
        from ray_tpu.util.usage_stats import record_library_usage

        record_library_usage("train")
        run_dir = self._run_dir()
        sweep_staging(run_dir)
        ckpt_manager = CheckpointManager(self.run_config.checkpoint_config)
        fc = self.run_config.failure_config
        max_failures = fc.max_failures
        failures = 0
        failovers = 0
        latest_checkpoint = self.resume_from_checkpoint
        history = []
        last_metrics: Optional[Dict[str, Any]] = None
        error: Optional[BaseException] = None
        iteration = 0
        reform = False
        # elastic lifecycle log — {"kind": "shrink"|"reform"|"regrow",
        # ...} — consumed by the MTTR harness (`perf.py
        # --elastic-recovery`) and the chaos tests' deterministic
        # assertions
        self._elastic_events: List[Dict[str, Any]] = []

        while True:
            executor = BackendExecutor(
                self.backend_config,
                self.scaling_config,
                experiment_name=os.path.basename(run_dir),
                trial_id=uuid.uuid4().hex[:8],
                storage_path=run_dir,
                failure_config=fc,
            )
            try:
                self._start_with_capacity_wait(executor, reform)
                width = len(executor.worker_group)
                if reform:
                    _mdefs.inc("rt_train_elastic_events_total",
                               tags={"kind": "reform"})
                    self._elastic_events.append({
                        "kind": "reform", "width": width,
                        "target": self.scaling_config.num_workers,
                        "iteration": iteration, "wall": time.time(),
                    })
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    checkpoint=latest_checkpoint,
                    datasets=self.datasets,
                )
                stop_requested = False
                pause_for_regrow = False
                regrow_last_probe = time.monotonic()
                t_last_round = time.monotonic()
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    # wall time between delivered rounds — the driver's
                    # view of step time, including report/backpressure
                    _mdefs.observe(
                        "rt_train_step_seconds",
                        time.monotonic() - t_last_round,
                    )
                    t_last_round = time.monotonic()
                    iteration += 1
                    rank0 = results[0]
                    metrics = dict(rank0.metrics or {})
                    metrics.setdefault("training_iteration", iteration)
                    metrics.setdefault("timestamp", time.time())
                    history.append(metrics)
                    last_metrics = metrics
                    reported = [r.checkpoint for r in results if r.checkpoint]
                    persisted = None
                    if reported:
                        try:
                            persisted = ckpt_manager.commit(
                                reported, run_dir, iteration, metrics
                            )
                            latest_checkpoint = persisted
                        except CheckpointCommitError as ce:
                            # e.g. a stop-boundary round where only a
                            # subset of writer ranks reported: the
                            # previous checkpoint stays `latest`
                            logger.warning(
                                "iteration %d checkpoint not published"
                                " (%s); keeping the previous one",
                                iteration, ce,
                            )
                    if self._result_callback is not None:
                        self._result_callback(metrics, persisted)
                    if not stop_requested and self._should_stop(metrics):
                        stop_requested = True
                        executor.request_stop_all()
                    # re-grow: a degraded elastic group periodically
                    # probes for its missing capacity; on success the
                    # ranks pause at the next step barrier and the
                    # group re-forms at full width
                    if (
                        fc.elastic
                        and not stop_requested
                        and not pause_for_regrow
                        and width < self.scaling_config.num_workers
                        and time.monotonic() - regrow_last_probe
                        >= fc.regrow_interval_s
                    ):
                        regrow_last_probe = time.monotonic()
                        if executor.probe_regrow():
                            pause_for_regrow = True
                            executor.request_stop_all()
                if pause_for_regrow:
                    _mdefs.inc("rt_train_elastic_events_total",
                               tags={"kind": "regrow"})
                    self._elastic_events.append({
                        "kind": "regrow", "width_from": width,
                        "iteration": iteration, "wall": time.time(),
                    })
                    executor.shutdown()
                    latest_checkpoint = (
                        ckpt_manager.latest_valid or latest_checkpoint
                    )
                    reform = True
                    continue
                error = None
                break
            except ElasticWorkerLost as e:
                failovers += 1
                _mdefs.inc("rt_train_elastic_events_total",
                           tags={"kind": "shrink"})
                self._elastic_events.append({
                    "kind": "shrink", "lost_ranks": dict(e.lost_ranks),
                    "width": e.width, "iteration": iteration,
                    "detected_wall": e.detected_at, "wall": time.time(),
                })
                logger.warning(
                    "elastic failover %d: %s — re-forming from latest "
                    "valid checkpoint", failovers, e,
                )
                if 0 <= fc.max_failovers < failovers:
                    error = TrainingFailedError(
                        f"training failed after {failovers} elastic "
                        f"failover(s): {e}"
                    )
                    break
                latest_checkpoint = (
                    ckpt_manager.latest_valid or latest_checkpoint
                )
                reform = True
            except TrainingWorkerError as e:
                failures += 1
                if max_failures >= 0 and failures > max_failures:
                    error = TrainingFailedError(
                        f"training failed after {failures} failure(s): {e}"
                    )
                    break
                latest_checkpoint = (
                    ckpt_manager.latest_valid or latest_checkpoint
                    if fc.elastic
                    else ckpt_manager.latest or latest_checkpoint
                )
            finally:
                executor.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=ckpt_manager.best or latest_checkpoint,
            error=error,
            path=run_dir,
            metrics_history=history,
            best_checkpoints=ckpt_manager.best_checkpoints,
        )

    def _start_with_capacity_wait(self, executor: BackendExecutor,
                                  reform: bool) -> None:
        """Start the executor; an elastic run whose cluster momentarily
        cannot place even ``min_workers`` waits with jittered backoff
        (never a constant-sleep redial loop) up to
        ``reform_deadline_s`` — preempted capacity routinely comes
        back within minutes."""
        fc = self.run_config.failure_config
        if not fc.elastic:
            executor.start()
            return
        deadline = time.monotonic() + fc.reform_deadline_s
        attempt = 0
        while True:
            try:
                executor.start(reform=reform)
                return
            except Exception as e:
                executor.shutdown()
                if not _is_capacity_error(e):
                    # deterministic failures (bad config, backend bug)
                    # must surface immediately with their real cause,
                    # not after reform_deadline_s of futile redialing
                    raise
                if time.monotonic() >= deadline:
                    raise TrainingFailedError(
                        f"cluster stayed below min_workers="
                        f"{fc.min_workers} for {fc.reform_deadline_s:.0f}s: "
                        f"{e}"
                    ) from e
                delay = backoff_delay_s(
                    attempt, base_s=0.5, cap_s=15.0,
                )
                logger.info(
                    "elastic start attempt %d failed (%s); retrying in "
                    "%.1fs", attempt + 1, e, delay,
                )
                time.sleep(delay)
                attempt += 1


class JaxTrainer(DataParallelTrainer):
    """The flagship trainer: JAX SPMD on TPU meshes (the reference's
    TorchTrainer analog, `train/torch/torch_trainer.py`)."""

    _default_backend_config = JaxConfig()

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
