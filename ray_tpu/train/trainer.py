"""Trainers: fit() orchestration over the BackendExecutor.

Reference: `train/base_trainer.py:111` BaseTrainer.fit,
`train/data_parallel_trainer.py:25` DataParallelTrainer.  Differences
by design: fit() drives the executor directly (the reference detours
through Tune — our Tune-equivalent wraps trainers via
`as_trainable()` the same way, see `ray_tpu/tune`).
"""

from __future__ import annotations

import os
import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingWorkerError
from ray_tpu.train.checkpoint import Checkpoint, persist_checkpoint
from ray_tpu.train.checkpoint_manager import CheckpointManager
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result


class TrainingFailedError(RuntimeError):
    """Raised by fit() when training fails beyond FailureConfig limits."""


class BaseTrainer:
    # per-iteration hook (metrics, persisted_checkpoint|None) used by the
    # Tune integration to forward reports to the trial (reference: the
    # trainable wrapper re-reporting, base_trainer.py:819)
    _result_callback = None

    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Wrap into a Tune Trainable (reference `base_trainer.py:819`);
        imported lazily to keep train usable without tune."""
        from ray_tpu.tune.trainable import wrap_trainer

        return wrap_trainer(self)


class DataParallelTrainer(BaseTrainer):
    """SPMD training: the same train_loop_per_worker on N workers.

    Reference: `train/data_parallel_trainer.py:25,428`.  The loop calls
    `ray_tpu.train.report(metrics, checkpoint=...)` each iteration; rank
    0's metrics become the run's reported metrics.
    """

    _default_backend_config: BackendConfig = BackendConfig()

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.backend_config = backend_config or self._default_backend_config

    # -- storage layout ------------------------------------------------
    def _run_dir(self) -> str:
        name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        d = os.path.join(self.run_config.storage_path, name)
        os.makedirs(d, exist_ok=True)
        return d

    def _should_stop(self, metrics: Dict[str, Any]) -> bool:
        stop = self.run_config.stop
        if not stop:
            return False
        for k, v in stop.items():
            if k == "training_iteration":
                if metrics.get("training_iteration", 0) >= v:
                    return True
            elif k in metrics and metrics[k] >= v:
                return True
        return False

    def fit(self) -> Result:
        from ray_tpu.util.usage_stats import record_library_usage

        record_library_usage("train")
        run_dir = self._run_dir()
        ckpt_manager = CheckpointManager(self.run_config.checkpoint_config)
        max_failures = self.run_config.failure_config.max_failures
        failures = 0
        latest_checkpoint = self.resume_from_checkpoint
        history = []
        last_metrics: Optional[Dict[str, Any]] = None
        error: Optional[BaseException] = None
        iteration = 0

        while True:
            executor = BackendExecutor(
                self.backend_config,
                self.scaling_config,
                experiment_name=os.path.basename(run_dir),
                trial_id=uuid.uuid4().hex[:8],
                storage_path=run_dir,
            )
            try:
                executor.start()
                executor.start_training(
                    self.train_loop_per_worker,
                    self.train_loop_config,
                    checkpoint=latest_checkpoint,
                    datasets=self.datasets,
                )
                while True:
                    results = executor.get_next_results()
                    if results is None:
                        break
                    iteration += 1
                    rank0 = results[0]
                    metrics = dict(rank0.metrics or {})
                    metrics.setdefault("training_iteration", iteration)
                    metrics.setdefault("timestamp", time.time())
                    history.append(metrics)
                    last_metrics = metrics
                    reported = [r.checkpoint for r in results if r.checkpoint]
                    persisted = None
                    if reported:
                        dest = None
                        for ck in reported:
                            dest = persist_checkpoint(ck, run_dir, iteration)
                        persisted = Checkpoint(dest)
                        persisted.update_metadata({"iteration": iteration})
                        ckpt_manager.register(persisted, metrics, iteration)
                        latest_checkpoint = persisted
                    if self._result_callback is not None:
                        self._result_callback(metrics, persisted)
                    if self._should_stop(metrics):
                        for w in executor.worker_group.workers:
                            w.request_stop.remote()
                error = None
                break
            except TrainingWorkerError as e:
                failures += 1
                if max_failures >= 0 and failures > max_failures:
                    error = TrainingFailedError(
                        f"training failed after {failures} failure(s): {e}"
                    )
                    break
                latest_checkpoint = ckpt_manager.latest or latest_checkpoint
            finally:
                executor.shutdown()

        return Result(
            metrics=last_metrics,
            checkpoint=ckpt_manager.best or latest_checkpoint,
            error=error,
            path=run_dir,
            metrics_history=history,
            best_checkpoints=ckpt_manager.best_checkpoints,
        )


class JaxTrainer(DataParallelTrainer):
    """The flagship trainer: JAX SPMD on TPU meshes (the reference's
    TorchTrainer analog, `train/torch/torch_trainer.py`)."""

    _default_backend_config = JaxConfig()

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        kwargs.setdefault("backend_config", jax_config or JaxConfig())
        super().__init__(train_loop_per_worker, **kwargs)
