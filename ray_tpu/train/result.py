"""Result of a training/tuning run (reference: `air/result.py`)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: Optional[Dict[str, Any]] = None
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: str = ""
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[Any] = field(default_factory=list)

    @property
    def config(self) -> Optional[Dict[str, Any]]:
        return (self.metrics or {}).get("config")

    def __repr__(self):
        err = f", error={type(self.error).__name__}" if self.error else ""
        return f"Result(metrics={self.metrics}{err}, path={self.path!r})"
