"""Train configuration dataclasses.

Surface mirrors the reference's `air/config.py` (`ScalingConfig`,
`RunConfig`, `FailureConfig`, `CheckpointConfig`) so reference users find
the same knobs — extended TPU-first: `ScalingConfig` speaks chips and
mesh topology, not GPUs-per-worker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class ScalingConfig:
    """How many workers and what each gets.

    Reference: `air/config.py` ScalingConfig (num_workers, use_gpu,
    resources_per_worker, placement_strategy).  TPU-native additions:

    - ``use_tpu`` / ``topology``: ask the scheduler for an
      ICI-contiguous sub-mesh ("4x4") instead of loose chips.
    - ``mesh_shape``: logical mesh axes each worker should build over
      its visible devices, e.g. ``{"dp": 2, "tp": 4}``.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None
    mesh_shape: Optional[Dict[str, int]] = None
    trainer_resources: Optional[Dict[str, float]] = None

    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
        else:
            res = {"CPU": 1.0}
            if self.use_tpu:
                res["TPU"] = 1.0
        return res

    @property
    def num_tpus_per_worker(self) -> float:
        return self._resources_per_worker_not_none().get("TPU", 0.0)


@dataclass
class FailureConfig:
    """Reference: `air/config.py` FailureConfig(max_failures), extended
    with the elastic-training contract (ROADMAP item 4: preemption-
    tolerant worker groups).

    - ``max_failures``: restarts granted for failures raised BY the
      user's train loop (unchanged semantics; -1 = unlimited).
    - ``elastic``: when True, a LOST worker (preempted host, SIGKILLed
      process, tripped circuit breaker) does not consume the
      ``max_failures`` budget and does not require full capacity to
      recover: the group re-forms at the widest placeable width in
      ``[min_workers, num_workers]``, restores from the latest atomic
      checkpoint (resharding as needed), and re-grows to full width
      when capacity returns.
    - ``min_workers``: smallest world size worth training at (default
      1).  Below it the trainer waits — with jittered backoff — up to
      ``reform_deadline_s`` before failing the run.
    - ``detect_poll_s``: executor-side polling granularity while
      waiting on worker results; bounds how long a hung ``execute``
      can mask a death signalled by the health plane.
    - ``drain_timeout_s``: how long surviving ranks get to reach the
      step barrier (their next ``report()``) before being torn down
      anyway — a survivor wedged inside a collective with a dead peer
      must not stall recovery.
    - ``reform_timeout_s``: per-width placement-group wait while
      re-forming (the shrink ladder tries num_workers, then
      num_workers-1, ... min_workers, each bounded by this).
    - ``reform_deadline_s``: total budget for capacity below
      ``min_workers`` before the run fails.
    - ``regrow_interval_s``: how often a degraded group probes for the
      missing capacity; a successful probe pauses ranks at the next
      step barrier and re-forms at full width.
    """

    max_failures: int = 0
    elastic: bool = False
    min_workers: int = 1
    detect_poll_s: float = 0.5
    drain_timeout_s: float = 5.0
    reform_timeout_s: float = 10.0
    reform_deadline_s: float = 300.0
    regrow_interval_s: float = 10.0
    max_failovers: int = -1  # elastic failovers allowed (-1 = unlimited)

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError("min_workers must be >= 1")
        for knob in ("detect_poll_s", "drain_timeout_s",
                     "reform_timeout_s", "reform_deadline_s",
                     "regrow_interval_s"):
            if getattr(self, knob) <= 0:
                raise ValueError(f"{knob} must be positive")

    @property
    def retries_enabled(self) -> bool:
        return self.max_failures != 0


@dataclass
class CheckpointConfig:
    """Reference: `air/config.py` CheckpointConfig — top-K retention by
    a score attribute."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


def _default_storage_path() -> str:
    return os.environ.get(
        "RT_STORAGE_PATH", os.path.expanduser("~/ray_tpu_results")
    )


@dataclass
class RunConfig:
    """Reference: `air/config.py` RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = _default_storage_path()
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
