"""Train configuration dataclasses.

Surface mirrors the reference's `air/config.py` (`ScalingConfig`,
`RunConfig`, `FailureConfig`, `CheckpointConfig`) so reference users find
the same knobs — extended TPU-first: `ScalingConfig` speaks chips and
mesh topology, not GPUs-per-worker.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class ScalingConfig:
    """How many workers and what each gets.

    Reference: `air/config.py` ScalingConfig (num_workers, use_gpu,
    resources_per_worker, placement_strategy).  TPU-native additions:

    - ``use_tpu`` / ``topology``: ask the scheduler for an
      ICI-contiguous sub-mesh ("4x4") instead of loose chips.
    - ``mesh_shape``: logical mesh axes each worker should build over
      its visible devices, e.g. ``{"dp": 2, "tp": 4}``.
    """

    num_workers: int = 1
    use_tpu: bool = False
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    topology: Optional[str] = None
    mesh_shape: Optional[Dict[str, int]] = None
    trainer_resources: Optional[Dict[str, float]] = None

    def _resources_per_worker_not_none(self) -> Dict[str, float]:
        if self.resources_per_worker is not None:
            res = dict(self.resources_per_worker)
        else:
            res = {"CPU": 1.0}
            if self.use_tpu:
                res["TPU"] = 1.0
        return res

    @property
    def num_tpus_per_worker(self) -> float:
        return self._resources_per_worker_not_none().get("TPU", 0.0)


@dataclass
class FailureConfig:
    """Reference: `air/config.py` FailureConfig(max_failures)."""

    max_failures: int = 0

    @property
    def retries_enabled(self) -> bool:
        return self.max_failures != 0


@dataclass
class CheckpointConfig:
    """Reference: `air/config.py` CheckpointConfig — top-K retention by
    a score attribute."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0
    checkpoint_at_end: bool = False

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


def _default_storage_path() -> str:
    return os.environ.get(
        "RT_STORAGE_PATH", os.path.expanduser("~/ray_tpu_results")
    )


@dataclass
class RunConfig:
    """Reference: `air/config.py` RunConfig."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    stop: Optional[Dict[str, Any]] = None
    verbose: int = 1

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = _default_storage_path()
        if self.failure_config is None:
            self.failure_config = FailureConfig()
        if self.checkpoint_config is None:
            self.checkpoint_config = CheckpointConfig()
