"""Torch backend + TorchTrainer.

Reference: `python/ray/train/torch/` — `TorchConfig`
(`train/torch/config.py:66`: TCP-store rendezvous +
`torch.distributed.init_process_group`), `TorchTrainer`
(`torch_trainer.py`), and the `prepare_model`/`prepare_data_loader`
loop utilities (`train_loop_utils.py`).

CPU-native here (this image ships torch CPU + gloo): rank 0 opens the
TCP store, every worker joins the gloo process group, and the training
loop uses standard torch DDP.  On TPU the JaxTrainer is the flagship;
this backend exists so reference TorchTrainer code ports unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig, _coordinator_addr
from ray_tpu.train.trainer import DataParallelTrainer
from ray_tpu.train.worker_group import WorkerGroup


@dataclass
class TorchConfig(BackendConfig):
    """Reference: `train/torch/config.py` TorchConfig."""

    backend: str = "gloo"
    init_timeout_s: float = 120.0

    @property
    def backend_cls(self):
        return _TorchBackend


def _init_torch_process_group(backend: str, init_method: str,
                              world_size: int, rank: int, timeout_s: float):
    import datetime

    import torch.distributed as dist

    # interface selection is the deployment's call (set
    # GLOO_SOCKET_IFNAME in runtime_env/env for multi-NIC hosts)
    dist.init_process_group(
        backend=backend,
        init_method=init_method,
        world_size=world_size,
        rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s),
    )


def _destroy_torch_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class _TorchBackend(Backend):
    """Reference: `train/torch/config.py:153` _TorchBackend."""

    def on_training_start(self, worker_group: WorkerGroup,
                          backend_config: TorchConfig):
        n = len(worker_group)
        if n <= 1:
            return
        import ray_tpu as rt

        host, port = worker_group.execute_single(0, _coordinator_addr)
        init_method = f"tcp://{host}:{port}"
        # rank 0 hosts the TCP store: start it first, then the rest join
        rank0 = worker_group.workers[0].execute.remote(
            _init_torch_process_group, backend_config.backend, init_method,
            n, 0, backend_config.init_timeout_s,
        )
        rest = [
            w.execute.remote(
                _init_torch_process_group, backend_config.backend,
                init_method, n, i, backend_config.init_timeout_s,
            )
            for i, w in enumerate(worker_group.workers)
            if i > 0
        ]
        rt.get([rank0, *rest])

    def on_shutdown(self, worker_group: WorkerGroup,
                    backend_config: TorchConfig):
        try:
            worker_group.execute(_destroy_torch_process_group)
        except Exception:
            pass


class TorchTrainer(DataParallelTrainer):
    """Reference: `train/torch/torch_trainer.py` — the same
    train_loop_per_worker contract as the reference's TorchTrainer;
    inside the loop use `prepare_model` for DDP and the standard
    `train.report` session API."""

    def __init__(self, train_loop_per_worker, *,
                 torch_config: Optional[TorchConfig] = None, **kwargs):
        kwargs.setdefault("backend_config", torch_config or TorchConfig())
        super().__init__(train_loop_per_worker, **kwargs)


def prepare_model(model):
    """Wrap in DistributedDataParallel when a process group is up
    (reference: `train_loop_utils.py` prepare_model)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_available() and dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across workers with DistributedSampler
    (reference: `train_loop_utils.py` prepare_data_loader).  The user's
    loader configuration is preserved; only the sampler is swapped (a
    batch_sampler-configured loader is rejected — pass batch_size
    instead).  Call `loader.sampler.set_epoch(e)` per epoch for fresh
    shuffles, as with any DistributedSampler."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_available() and dist.is_initialized()
            and dist.get_world_size() > 1):
        return loader
    if loader.batch_size is None:
        raise ValueError(
            "prepare_data_loader cannot re-shard a batch_sampler-based "
            "DataLoader; construct it with batch_size instead"
        )
    sampler = DistributedSampler(loader.dataset)
    return DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=sampler,
        num_workers=loader.num_workers,
        collate_fn=loader.collate_fn,
        drop_last=loader.drop_last,
        pin_memory=loader.pin_memory,
        timeout=loader.timeout,
        worker_init_fn=loader.worker_init_fn,
        generator=loader.generator,
        prefetch_factor=(loader.prefetch_factor
                         if loader.num_workers > 0 else None),
        persistent_workers=loader.persistent_workers,
    )
