"""WorkerGroup: a gang of training worker actors.

Reference: `train/_internal/worker_group.py:102` — N actors created with
per-worker resources, placed by a placement group, with `execute` /
`execute_async` / `execute_single` RPC helpers.  The TrainWorker actor
additionally hosts the training session thread (reference
`_internal/session.py` `_StartTraining` + result queue).
"""

from __future__ import annotations

import os
import threading
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.train import session as _session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _Session, _TrainingResult
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)


class TrainWorker:
    """Actor hosting one training session."""

    def __init__(self, env_vars: Optional[Dict[str, str]] = None):
        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        self._session: Optional[_Session] = None
        self._thread: Optional[threading.Thread] = None

    # -- generic RPC ---------------------------------------------------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env_vars: Dict[str, str]):
        os.environ.update(env_vars)

    def node_info(self):
        return {"pid": os.getpid(), "hostname": os.uname().nodename}

    # -- training session ----------------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]],
        context: TrainContext,
        checkpoint: Optional[Checkpoint],
        datasets: Optional[Dict[str, Any]] = None,
    ):
        assert self._thread is None or not self._thread.is_alive(), (
            "training already running"
        )
        sess = _Session(context, checkpoint, datasets)
        self._session = sess

        import inspect

        try:
            takes_config = len(inspect.signature(train_fn).parameters) >= 1
        except (TypeError, ValueError):
            takes_config = True

        def _run():
            _session_mod._set_session(sess)
            try:
                if takes_config:
                    train_fn(config if config is not None else {})
                else:
                    train_fn()
                sess.result_queue.put(_TrainingResult(done=True))
            except StopIteration:
                sess.result_queue.put(_TrainingResult(done=True))
            except BaseException as e:  # noqa: BLE001 - forwarded to driver
                e._rt_traceback = traceback.format_exc()  # type: ignore[attr-defined]
                sess.result_queue.put(_TrainingResult(done=True, error=e))
            finally:
                _session_mod._set_session(None)

        self._thread = threading.Thread(target=_run, daemon=True, name="train_loop")
        self._thread.start()
        return True

    def get_next_result(self) -> _TrainingResult:
        assert self._session is not None, "no training session"
        return self._session.result_queue.get()

    def request_stop(self):
        if self._session is not None:
            self._session.stop_requested.set()

    def finish(self, timeout: float = 10.0) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True


@dataclass
class WorkerMetadata:
    rank: int
    node_id: Optional[str]
    pid: int


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Optional[Dict[str, float]] = None,
        placement_strategy: str = "PACK",
        env_vars: Optional[Dict[str, str]] = None,
    ):
        self.num_workers = num_workers
        res = dict(resources_per_worker or {"CPU": 1.0})
        self._pg: Optional[PlacementGroup] = placement_group(
            [dict(res) for _ in range(num_workers)], strategy=placement_strategy
        )
        if not self._pg.ready(timeout=60.0):
            remove_placement_group(self._pg)
            raise rt.exceptions.RayTpuError(
                f"could not reserve {num_workers} x {res} worker bundles"
            )
        opts = dict(
            num_cpus=res.pop("CPU", 0.0),
            num_tpus=res.pop("TPU", 0.0),
            resources=res or None,
            max_concurrency=2,  # get_next_result blocks while the loop runs
        )
        cls = rt.remote(TrainWorker)
        self.workers: List[rt.ActorHandle] = [
            cls.options(
                **opts,
                placement_group=self._pg,
                placement_group_bundle_index=i,
            ).remote(env_vars)
            for i in range(num_workers)
        ]

    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return rt.get(self.execute_async(fn, *args, **kwargs))

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return rt.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def __len__(self):
        return self.num_workers

    def shutdown(self):
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
