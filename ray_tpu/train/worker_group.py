"""WorkerGroup: a gang of training worker actors.

Reference: `train/_internal/worker_group.py:102` — N actors created with
per-worker resources, placed by a placement group, with `execute` /
`execute_async` / `execute_single` RPC helpers.  The TrainWorker actor
additionally hosts the training session thread (reference
`_internal/session.py` `_StartTraining` + result queue).

Elastic extensions (ROADMAP item 4):

- **widest-fit reserve**: with ``min_workers`` set, the placement-group
  reservation walks num_workers → min_workers and takes the widest
  width the cluster can place within a bounded wait — a preempted host
  shrinks the gang instead of failing it.
- **health monitor**: the group subscribes to the runtime's health
  plane — the controller's ``actor_state``/``node_dead`` pubsub
  channels and `core/rpc.py`'s circuit-breaker transition hook — so a
  lost rank is reported within a bounded window instead of being
  discovered via a hung ``execute``.
- **hardened finish/shutdown**: ``request_stop`` is propagated to ALL
  ranks before any join, every join is bounded, and the first worker
  exception is surfaced instead of a generic timeout.
"""

from __future__ import annotations

import logging
import os
import queue as _queue
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu as rt
from ray_tpu.core import rpc
from ray_tpu.train import session as _session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import TrainContext, _Session, _TrainingResult
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    remove_placement_group,
)

logger = logging.getLogger(__name__)


def _put_final(sess: _Session, res: _TrainingResult) -> None:
    """Deliver the session thread's TERMINAL result.  On the normal
    path (including a graceful stop) this is a plain blocking put —
    the executor is still consuming in lockstep.  When the session is
    ABANDONED there is no consumer: stale entries are dropped so the
    final done/error result can never deadlock against a full queue."""
    if not sess.abandoned.is_set():
        sess.result_queue.put(res)
        return
    while True:
        try:
            sess.result_queue.put_nowait(res)
            return
        except _queue.Full:
            try:
                sess.result_queue.get_nowait()
            except _queue.Empty:
                logger.debug("final-result queue race; retrying put")


class TrainWorker:
    """Actor hosting one training session."""

    def __init__(self, env_vars: Optional[Dict[str, str]] = None):
        for k, v in (env_vars or {}).items():
            os.environ[k] = v
        self._session: Optional[_Session] = None
        self._thread: Optional[threading.Thread] = None
        self._last_error: Optional[str] = None

    # -- generic RPC ---------------------------------------------------
    def execute(self, fn: Callable, *args, **kwargs):
        return fn(*args, **kwargs)

    def set_env(self, env_vars: Dict[str, str]):
        os.environ.update(env_vars)

    def node_info(self):
        return {"pid": os.getpid(), "hostname": os.uname().nodename}

    # -- training session ----------------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]],
        context: TrainContext,
        checkpoint: Optional[Checkpoint],
        datasets: Optional[Dict[str, Any]] = None,
    ):
        assert self._thread is None or not self._thread.is_alive(), (
            "training already running"
        )
        sess = _Session(context, checkpoint, datasets)
        self._session = sess
        self._last_error = None

        import inspect

        try:
            takes_config = len(inspect.signature(train_fn).parameters) >= 1
        except (TypeError, ValueError):
            takes_config = True

        def _run():
            _session_mod._set_session(sess)
            try:
                if takes_config:
                    train_fn(config if config is not None else {})
                else:
                    train_fn()
                _put_final(sess, _TrainingResult(done=True))
            except StopIteration:
                _put_final(sess, _TrainingResult(done=True))
            except BaseException as e:  # noqa: BLE001 - forwarded to driver
                e._rt_traceback = traceback.format_exc()  # type: ignore[attr-defined]
                self._last_error = f"{type(e).__name__}: {e}"
                _put_final(sess, _TrainingResult(done=True, error=e))
            finally:
                _session_mod._set_session(None)

        self._thread = threading.Thread(target=_run, daemon=True, name="train_loop")
        self._thread.start()
        return True

    def get_next_result(self) -> _TrainingResult:
        assert self._session is not None, "no training session"
        return self._session.result_queue.get()

    def request_stop(self, drain: bool = False):
        """Graceful (default): the loop unwinds at its next report()
        AFTER delivering that round — the executor keeps consuming, so
        rounds stay complete and committed checkpoints stay whole.

        ``drain=True`` additionally marks the session ABANDONED (the
        executor stopped consuming: elastic drain, teardown) and
        unblocks a session thread parked in report()'s backpressure
        put by discarding the stale per-step result.  A TERMINAL
        result (done/error) is re-queued, never swallowed — a loop
        that finished naturally just as the stop landed has nothing
        further to put, and discarding its done would hang the
        driver's next get_next_result forever."""
        sess = self._session
        if sess is None:
            return
        sess.stop_requested.set()
        if not drain:
            return
        sess.abandoned.set()
        try:
            item = sess.result_queue.get_nowait()
        except _queue.Empty:
            return
        if item.done or item.error is not None:
            try:
                sess.result_queue.put_nowait(item)
            except _queue.Full:
                # only possible if a newer terminal result landed in
                # the gap; equivalent signal, drop this one
                logger.debug("terminal result superseded during "
                             "request_stop")

    def finish(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Bounded join of the session thread.  Returns
        ``{"clean": bool, "error": str|None}`` so the driver can
        surface the loop's actual exception instead of a generic
        timeout."""
        clean = True
        if self._thread is not None:
            self._thread.join(timeout)
            clean = not self._thread.is_alive()
        return {"clean": clean, "error": self._last_error}


@dataclass
class WorkerMetadata:
    rank: int
    node_id: Optional[str]
    pid: int


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Optional[Dict[str, float]] = None,
        placement_strategy: str = "PACK",
        env_vars: Optional[Dict[str, str]] = None,
        min_workers: Optional[int] = None,
        reserve_timeout_s: float = 60.0,
        fallback_timeout_s: float = 10.0,
    ):
        res = dict(resources_per_worker or {"CPU": 1.0})
        self._pg, width = self._reserve(
            num_workers, min_workers, res, placement_strategy,
            reserve_timeout_s, fallback_timeout_s,
        )
        self.num_workers = width
        self.requested_workers = num_workers
        opts = dict(
            num_cpus=res.pop("CPU", 0.0),
            num_tpus=res.pop("TPU", 0.0),
            resources=res or None,
            max_concurrency=2,  # get_next_result blocks while the loop runs
        )
        cls = rt.remote(TrainWorker)
        self.workers: List[rt.ActorHandle] = []
        try:
            for i in range(width):
                self.workers.append(self._create_worker(
                    cls, opts, i, env_vars
                ))
        except BaseException:
            # a half-built gang must release everything it holds: a
            # leaked CREATED placement group would permanently starve
            # every later (elastic re-form) reservation attempt
            for w in self.workers:
                try:
                    rt.kill(w)
                except Exception as e:
                    logger.debug("cleanup kill failed: %s", e)
            self.workers = []
            try:
                remove_placement_group(self._pg)
            except Exception as e:
                logger.debug("cleanup PG removal failed: %s", e)
            raise
        # -- health-monitor state (idle until start_monitor) ----------
        self._lost: Dict[int, str] = {}
        self._lost_lock = threading.Lock()
        self._on_lost: Optional[Callable[[int, str], None]] = None
        self._monitor_stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None

    def _create_worker(self, cls, opts: Dict[str, Any], bundle_index: int,
                       env_vars: Optional[Dict[str, str]]):
        """Create one rank's actor inside its reserved bundle.
        Transient placement refusals ("resources no longer available",
        "no idle worker") are expected right after a previous gang's
        teardown — the daemon refunds a killed worker's resources
        asynchronously — and the bundle GUARANTEES the capacity
        exists, so they are retried with jittered backoff instead of
        failing the (re-)form."""
        from ray_tpu.core.retry import backoff_delay_s

        attempt = 0
        while True:
            try:
                return cls.options(
                    **opts,
                    placement_group=self._pg,
                    placement_group_bundle_index=bundle_index,
                ).remote(env_vars)
            except rt.exceptions.RayTpuError as e:
                transient = ("resources no longer available" in str(e)
                             or "no idle worker" in str(e))
                if not transient or attempt >= 6:
                    raise
                delay = backoff_delay_s(attempt, base_s=0.2, cap_s=2.0)
                logger.debug(
                    "worker %d creation rejected (%s); retrying in "
                    "%.2fs", bundle_index, e, delay,
                )
                time.sleep(delay)
                attempt += 1

    @staticmethod
    def _reserve(
        num_workers: int,
        min_workers: Optional[int],
        res: Dict[str, float],
        strategy: str,
        reserve_timeout_s: float,
        fallback_timeout_s: float,
    ) -> Tuple[PlacementGroup, int]:
        """Widest-fit gang reservation: try full width first (with the
        generous first-attempt timeout), then walk down to
        ``min_workers`` with the shorter fallback timeout per width.
        Every failed attempt removes its pending placement group so an
        unplaceable request cannot squat on capacity."""
        floor = num_workers if min_workers is None else max(1, min_workers)
        timeout = reserve_timeout_s
        for width in range(num_workers, floor - 1, -1):
            pg = placement_group(
                [dict(res) for _ in range(width)], strategy=strategy
            )
            if pg.ready(timeout=timeout):
                if width < num_workers:
                    logger.warning(
                        "worker group degraded: reserved %d/%d bundles of "
                        "%s", width, num_workers, res,
                    )
                return pg, width
            remove_placement_group(pg)
            timeout = fallback_timeout_s
        raise rt.exceptions.RayTpuError(
            f"could not reserve even {floor} x {res} worker bundles "
            f"(requested {num_workers})"
        )

    # ------------------------------------------------------------------
    # health monitor: bounded-window loss detection
    # ------------------------------------------------------------------
    def start_monitor(self, on_lost: Callable[[int, str], None]) -> None:
        """Report lost ranks via `on_lost(rank, cause)` (each rank at
        most once), fed by three independent signals:

        - controller ``actor_state`` pubsub: a worker actor marked
          DEAD/RESTARTING (missed actor heartbeat, worker SIGKILL);
        - controller ``node_dead`` pubsub: the host carrying a rank
          left the cluster (preemption) — the fastest signal;
        - `rpc.add_breaker_listener`: the rank's circuit breaker
          tripped OPEN (black-holed peer that never cleanly died).

        The callback runs on the monitor/notifier thread and must be
        fast and non-blocking."""
        if self._monitor_thread is not None and self._monitor_thread.is_alive():
            return
        self._on_lost = on_lost
        self._monitor_stop.clear()
        rpc.add_breaker_listener(self._breaker_event)
        self._monitor_thread = threading.Thread(
            target=self._monitor_main, daemon=True, name="train-wg-monitor"
        )
        self._monitor_thread.start()

    def stop_monitor(self, timeout_s: float = 5.0) -> None:
        rpc.remove_breaker_listener(self._breaker_event)
        self._monitor_stop.set()
        t = self._monitor_thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
        self._monitor_thread = None

    def lost_ranks(self) -> Dict[int, str]:
        with self._lost_lock:
            return dict(self._lost)

    def mark_lost(self, rank: int, cause: str) -> None:
        """Idempotent: the first signal for a rank wins; later signals
        (a breaker trip racing the DEAD publish) are no-ops."""
        with self._lost_lock:
            if rank in self._lost or rank >= len(self.workers):
                return
            self._lost[rank] = cause
        logger.warning("train worker rank %d lost: %s", rank, cause)
        cb = self._on_lost
        if cb is not None:
            try:
                cb(rank, cause)
            except Exception:
                logger.exception("on_lost callback failed for rank %d", rank)

    def _actor_rank_map(self) -> Dict[bytes, int]:
        return {
            w._actor_id.binary(): i for i, w in enumerate(self.workers)
        }

    def _worker_addresses(self) -> Dict[int, Tuple[str, str]]:
        """rank -> (node_id, worker_id), best-effort from the runtime's
        actor-address table (populated at actor creation)."""
        try:
            from ray_tpu.core.runtime import get_runtime

            table = get_runtime()._actor_addr
        except Exception as e:
            logger.debug("actor address table unavailable: %s", e)
            return {}
        out: Dict[int, Tuple[str, str]] = {}
        for i, w in enumerate(self.workers):
            addr = table.get(w._actor_id.binary())
            if addr is not None:
                out[i] = tuple(addr)
        return out

    def _breaker_event(self, address: str, old: str, new: str) -> None:
        if new != rpc.CircuitBreaker.OPEN or not address.startswith("actor:"):
            return
        for rank, (node_id, worker_id) in self._worker_addresses().items():
            if address == f"actor:{node_id}:{worker_id}":
                self.mark_lost(rank, f"circuit breaker open ({address})")
                return

    def _monitor_main(self) -> None:
        from ray_tpu.core.runtime import get_runtime

        subs = []
        try:
            for channel in ("actor_state", "node_dead"):
                subs.append((channel, get_runtime().subscribe(channel)))
        except Exception as e:
            # pubsub unavailable (runtime tearing down): breaker events
            # still flow through the listener hook
            logger.debug("worker-group health subscribe failed: %s", e)
        try:
            while not self._monitor_stop.is_set():
                if not subs:
                    self._monitor_stop.wait(0.2)
                    continue
                for channel, sub in subs:
                    try:
                        msg = sub.next_message(timeout=0.2)
                    except _queue.Empty:
                        continue
                    except Exception as e:
                        logger.debug("health subscription broke: %s", e)
                        self._monitor_stop.wait(0.2)
                        continue
                    self._handle_health_msg(channel, msg)
        finally:
            for _, sub in subs:
                try:
                    sub.close()
                except Exception as e:
                    logger.debug("closing health subscription: %s", e)

    def _handle_health_msg(self, channel: str, msg) -> None:
        if not isinstance(msg, dict):
            return
        if channel == "actor_state":
            state = msg.get("state")
            if state not in ("DEAD", "RESTARTING"):
                return
            rank = self._actor_rank_map().get(msg.get("actor_id"))
            if rank is not None:
                cause = msg.get("cause", "actor heartbeat missed")
                self.mark_lost(rank, f"actor {state}: {cause}")
        elif channel == "node_dead":
            node_id = msg.get("node_id")
            for rank, (nid, _wid) in self._worker_addresses().items():
                if nid == node_id:
                    self.mark_lost(
                        rank, f"node {str(node_id)[:8]} died: "
                        f"{msg.get('reason', '?')}"
                    )

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def execute_async(self, fn: Callable, *args, **kwargs):
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return rt.get(self.execute_async(fn, *args, **kwargs))

    def execute_single(self, rank: int, fn: Callable, *args, **kwargs) -> Any:
        return rt.get(self.workers[rank].execute.remote(fn, *args, **kwargs))

    def __len__(self):
        return self.num_workers

    # ------------------------------------------------------------------
    # teardown
    # ------------------------------------------------------------------
    def request_stop_all(self, drain: bool = False) -> None:
        """Fire-and-forget stop to every rank — the step barrier: each
        surviving loop unwinds at its next report().  ``drain=True``
        marks the sessions abandoned (no consumer remains); see
        TrainWorker.request_stop."""
        for i, w in enumerate(self.workers):
            try:
                w.request_stop.remote(drain)
            except Exception as e:
                logger.debug("request_stop to rank %d failed: %s", i, e)

    def finish(self, timeout_s: float = 30.0, raise_on_error: bool = True
               ) -> List[Dict[str, Any]]:
        """Stop and join every rank: `request_stop` is propagated to
        ALL ranks before any join, every join is bounded by the shared
        `timeout_s` deadline, and (with `raise_on_error`) the FIRST
        worker exception is raised instead of a generic timeout.
        Returns the per-rank ``{"clean", "error"}`` statuses."""
        if not self.workers:
            return []
        # finish abandons the sessions: nothing consumes results past
        # this point, so blocked reporters must be drained loose
        self.request_stop_all(drain=True)
        # one shared grace over the in-actor join, NOT per rank: total
        # wall time stays ~timeout_s regardless of group width (the
        # joins themselves run concurrently server-side; only the
        # result fetches are sequential, each bounded by what is left
        # of the shared deadline)
        deadline = time.monotonic() + timeout_s + 2.0
        join_s = max(0.5, timeout_s * 0.8)
        refs = []
        for w in self.workers:
            try:
                refs.append(w.finish.remote(join_s))
            except Exception as e:
                # not swallowed: carried into the rank's status below
                logger.debug("finish submit failed: %s", e)
                refs.append(e)
        statuses: List[Dict[str, Any]] = []
        first_error: Optional[Tuple[int, str]] = None
        for rank, ref in enumerate(refs):
            if isinstance(ref, Exception):
                st = {"clean": False,
                      "error": f"{type(ref).__name__}: {ref}"}
            else:
                remaining = max(0.1, deadline - time.monotonic())
                try:
                    st = rt.get(ref, timeout=remaining)
                except Exception as e:
                    # not swallowed: becomes the rank's reported error
                    logger.debug("finish join for rank %d: %s", rank, e)
                    st = {"clean": False,
                          "error": f"{type(e).__name__}: {e}"}
            statuses.append(st)
            if first_error is None and st.get("error"):
                first_error = (rank, st["error"])
        if raise_on_error and first_error is not None:
            raise rt.exceptions.RayTpuError(
                f"worker rank {first_error[0]} failed during finish: "
                f"{first_error[1]}"
            )
        return statuses

    def shutdown(self):
        self.stop_monitor()
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception as e:
                logger.debug("kill of train worker failed: %s", e)
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception as e:
                logger.debug("placement group removal failed: %s", e)
            self._pg = None
