"""BackendExecutor: drives the worker group through a training run.

Reference: `train/_internal/backend_executor.py:68` — start the
WorkerGroup, run Backend hooks, kick off training on every worker, poll
per-iteration results, surface worker failures as TrainingWorkerError
so the trainer can restart the group (reference FailureConfig path).

Elastic path (ROADMAP item 4): instead of discovering a dead rank via a
hung `execute`, the executor subscribes the WorkerGroup to the health
plane (actor_state/node_dead pubsub + circuit-breaker transitions) and
polls results with a bounded timeout.  On loss it pauses surviving
ranks at a step barrier (request_stop → their next report() unwinds),
drains them within a bounded window, tears the group down, and raises
`ElasticWorkerLost` so the trainer can re-form at a smaller width and
restore from the latest atomic checkpoint.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import FailureConfig, ScalingConfig
from ray_tpu.train.session import TrainContext, _TrainingResult
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)

logger = logging.getLogger(__name__)


class TrainingWorkerError(Exception):
    """A worker failed mid-training; the group must be restarted."""


class ElasticWorkerLost(TrainingWorkerError):
    """A rank was lost while `FailureConfig(elastic=True)`: the group
    was drained and torn down; the trainer re-forms it (possibly
    narrower) and resumes from the latest atomic checkpoint."""

    def __init__(self, lost_ranks: Dict[int, str], width: int,
                 detected_at: float):
        self.lost_ranks = dict(lost_ranks)
        self.width = width
        self.detected_at = detected_at  # wall clock of first detection
        causes = ", ".join(
            f"rank {r}: {c}" for r, c in sorted(self.lost_ranks.items())
        )
        super().__init__(
            f"lost {len(self.lost_ranks)}/{width} training worker(s) "
            f"({causes})"
        )


def _split_datasets(
    datasets: Optional[Dict[str, Any]], n: int, *, elastic: bool = False
) -> List[Dict[str, Any]]:
    """Per-worker dataset shards.  `Dataset`s split via streaming_split
    (reference `train/_internal/data_config.py`); lists shard
    round-robin; everything else is replicated.

    Elastic runs split with ``elastic=True``: the split coordinator is
    cached on the dataset, so a re-form after a mesh shrink/re-grow
    RESHARDS the in-progress epoch to the new width — in-flight blocks
    of lost ranks are redelivered to survivors, consumed blocks are
    never replayed (exactly-once ingest across the transition).  The
    reshard rides the same loss signals the WorkerGroup monitor uses:
    re-formation is only ever initiated by that detection plane."""
    shards: List[Dict[str, Any]] = [{} for _ in range(n)]
    for name, ds in (datasets or {}).items():
        if hasattr(ds, "streaming_split"):
            for i, shard in enumerate(ds.streaming_split(n, elastic=elastic)):
                shards[i][name] = shard
        elif isinstance(ds, (list, tuple)):
            for i in range(n):
                shards[i][name] = list(ds[i::n])
        else:
            for i in range(n):
                shards[i][name] = ds
    return shards


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        experiment_name: str = "",
        trial_id: str = "",
        storage_path: str = "",
        failure_config: Optional[FailureConfig] = None,
    ):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._experiment_name = experiment_name
        self._trial_id = trial_id
        self._storage_path = storage_path
        self._failure_config = failure_config or FailureConfig()
        self.worker_group: Optional[WorkerGroup] = None
        self._training_started = False
        self._lost_event = threading.Event()
        self._lost_detected_wall: Optional[float] = None

    @property
    def elastic(self) -> bool:
        return self._failure_config.elastic

    def start(self, reform: bool = False):
        fc = self._failure_config
        kwargs: Dict[str, Any] = {}
        if fc.elastic:
            kwargs = dict(
                # a floor above the requested width is a contradiction,
                # not a capacity condition: clamp it so the reserve
                # ladder is never empty (which would redial for the
                # whole reform_deadline_s with a misleading error)
                min_workers=min(fc.min_workers,
                                self._scaling.num_workers),
                # re-forms probe the full width briefly before walking
                # down; the first start keeps the generous default
                reserve_timeout_s=(
                    fc.reform_timeout_s if reform else 60.0
                ),
                fallback_timeout_s=fc.reform_timeout_s,
            )
        self.worker_group = WorkerGroup(
            num_workers=self._scaling.num_workers,
            resources_per_worker=self._scaling._resources_per_worker_not_none(),
            placement_strategy=self._scaling.placement_strategy,
            **kwargs,
        )
        if fc.elastic:
            self.worker_group.start_monitor(self._on_worker_lost)
        try:
            self._backend.on_start(self.worker_group, self._backend_config)
        except Exception as e:
            self._abort_if_elastic(e)
            raise

    def _on_worker_lost(self, rank: int, cause: str) -> None:
        """Health-plane callback (monitor/notifier thread): stamp the
        detection time, then pause survivors at the step barrier
        immediately — the sooner stop_requested is set, the sooner
        their next report() unwinds instead of entering a collective
        with a dead peer."""
        if self._lost_detected_wall is None:
            self._lost_detected_wall = time.time()
        self._lost_event.set()
        wg = self.worker_group
        if wg is not None and self._training_started:
            # a loss means the executor is about to abandon this
            # round's results: drain so no survivor stays parked in a
            # backpressure put
            wg.request_stop_all(drain=True)

    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]],
        checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        assert self.worker_group is not None, "call start() first"
        try:
            # the rendezvous (collective group / jax.distributed init)
            # and the session kick-off both block on worker RPCs: a
            # rank preempted DURING formation — exactly when a
            # preemption wave is still in progress — must fail over,
            # not abort fit() with a raw worker-died error
            self._backend.on_training_start(
                self.worker_group, self._backend_config
            )
            n = len(self.worker_group)
            shards = _split_datasets(
                datasets, n, elastic=self._failure_config.elastic
            )
            refs = []
            for rank, worker in enumerate(self.worker_group.workers):
                ctx = TrainContext(
                    world_size=n,
                    world_rank=rank,
                    local_rank=rank,  # single-host group so far
                    local_world_size=n,
                    experiment_name=self._experiment_name,
                    trial_id=self._trial_id,
                    mesh_shape=self._scaling.mesh_shape,
                    storage_path=self._storage_path,
                )
                if self._failure_config.elastic:
                    ctx.extra["elastic"] = True
                    ctx.extra["target_world_size"] = self._scaling.num_workers
                refs.append(
                    worker.start_training.remote(
                        train_fn, config, ctx, checkpoint, shards[rank]
                    )
                )
            rt.get(refs)
        except Exception as e:
            self._abort_if_elastic(e)
            raise
        self._training_started = True
        self._done = [False] * n

    def _abort_if_elastic(self, e: Exception) -> None:
        """Route a formation-window failure into the elastic failover
        path (raises ElasticWorkerLost) when elastic is on AND the
        failure is death-shaped (a rank/host went away) — a
        deterministic config/backend error must surface as itself, not
        loop as failovers forever."""
        if not self._failure_config.elastic or self.worker_group is None:
            return
        death_like = isinstance(e, (
            rt.exceptions.ActorDiedError,
            rt.exceptions.WorkerCrashedError,
            rt.exceptions.NodeDiedError,
        )) or any(s in str(e).lower() for s in (
            "died", "is dead", "worker_died", "connection lost",
            "disconnected",
        ))
        if not (self.worker_group.lost_ranks() or death_like):
            return
        if not self.worker_group.lost_ranks():
            self.worker_group.mark_lost(-1, f"group formation failed: {e}")
        self._elastic_abort()

    def get_next_results(self) -> Optional[List[_TrainingResult]]:
        """One result per still-running worker; None once all finished.
        All workers report in lockstep (same number of report() calls),
        as the reference requires.

        Elastic runs poll with `detect_poll_s` granularity so a rank
        lost mid-collective surfaces within a bounded window via the
        health plane instead of hanging this call forever."""
        assert self._training_started
        wg = self.worker_group
        live = [i for i, d in enumerate(self._done) if not d]
        if not live:
            return None
        refs = [wg.workers[i].get_next_result.remote() for i in live]
        elastic = self._failure_config.elastic
        while True:
            if elastic and (self._lost_event.is_set() or wg.lost_ranks()):
                self._elastic_abort()
            try:
                results: List[_TrainingResult] = rt.get(
                    refs,
                    timeout=(
                        self._failure_config.detect_poll_s
                        if elastic else None
                    ),
                )
                break
            except rt.exceptions.GetTimeoutError:
                continue
            except Exception as e:
                if elastic:
                    # the death surfaced through the call path before
                    # the health plane published it: attribute it to
                    # the exact rank(s) whose result refs are poisoned
                    for i, ref in zip(live, refs):
                        try:
                            rt.get([ref], timeout=0.05)
                        except rt.exceptions.GetTimeoutError:
                            continue
                        except Exception as pe:
                            # not swallowed: recorded as the loss cause
                            logger.debug("rank %d ref poisoned: %s", i, pe)
                            wg.mark_lost(i, f"worker call failed: {pe}")
                    if not wg.lost_ranks():
                        wg.mark_lost(-1, f"worker call failed: {e}")
                    self._elastic_abort()
                raise TrainingWorkerError(f"training worker died: {e}") from e
        out: List[_TrainingResult] = []
        for i, res in zip(live, results):
            if res.error is not None:
                raise TrainingWorkerError(
                    f"worker {i} failed: {res.error!r}\n"
                    + getattr(res.error, "_rt_traceback", "")
                ) from res.error
            if res.done:
                self._done[i] = True
            else:
                out.append(res)
        if not out and all(self._done):
            return None
        return out if out else self.get_next_results()

    def _elastic_abort(self):
        """Shrink entry point: pause survivors at the step barrier,
        drain them within `drain_timeout_s` (a survivor wedged in a
        collective with the dead peer is torn down anyway), then raise
        `ElasticWorkerLost` for the trainer's re-form loop."""
        wg = self.worker_group
        lost = wg.lost_ranks()
        width = len(wg)
        detected = self._lost_detected_wall or time.time()
        try:
            wg.finish(
                timeout_s=self._failure_config.drain_timeout_s,
                raise_on_error=False,
            )
        except Exception as e:
            logger.debug("elastic drain failed: %s", e)
        self.shutdown()
        raise ElasticWorkerLost(lost or {-1: "worker lost"}, width, detected)

    def request_stop_all(self) -> None:
        if self.worker_group is not None:
            self.worker_group.request_stop_all()

    def probe_regrow(self, timeout_s: float = 2.0) -> bool:
        """Can the missing capacity be placed right now?  Probes with a
        placement group for the DELTA only (the group's own bundles are
        released at re-form time, so delta + held == full width); the
        probe PG is always removed — it must never squat on capacity."""
        wg = self.worker_group
        if wg is None:
            return False
        delta = wg.requested_workers - len(wg)
        if delta <= 0:
            return False
        res = self._scaling._resources_per_worker_not_none()
        pg = placement_group(
            [dict(res) for _ in range(delta)],
            strategy=self._scaling.placement_strategy,
        )
        try:
            ok = pg.ready(timeout=timeout_s)
        finally:
            try:
                remove_placement_group(pg)
            except Exception as e:
                logger.debug("regrow probe PG removal failed: %s", e)
        return ok

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception as e:
                logger.debug("backend on_shutdown failed: %s", e)
            self.worker_group.shutdown()
            self.worker_group = None
        self._training_started = False
