"""BackendExecutor: drives the worker group through a training run.

Reference: `train/_internal/backend_executor.py:68` — start the
WorkerGroup, run Backend hooks, kick off training on every worker, poll
per-iteration results, surface worker failures as TrainingWorkerError
so the trainer can restart the group (reference FailureConfig path).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.session import TrainContext, _TrainingResult
from ray_tpu.train.worker_group import WorkerGroup


class TrainingWorkerError(Exception):
    """A worker failed mid-training; the group must be restarted."""


def _split_datasets(
    datasets: Optional[Dict[str, Any]], n: int
) -> List[Dict[str, Any]]:
    """Per-worker dataset shards.  `Dataset`s split via streaming_split
    (reference `train/_internal/data_config.py`); lists shard
    round-robin; everything else is replicated."""
    shards: List[Dict[str, Any]] = [{} for _ in range(n)]
    for name, ds in (datasets or {}).items():
        if hasattr(ds, "streaming_split"):
            for i, shard in enumerate(ds.streaming_split(n)):
                shards[i][name] = shard
        elif isinstance(ds, (list, tuple)):
            for i in range(n):
                shards[i][name] = list(ds[i::n])
        else:
            for i in range(n):
                shards[i][name] = ds
    return shards


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        experiment_name: str = "",
        trial_id: str = "",
        storage_path: str = "",
    ):
        self._backend_config = backend_config
        self._backend: Backend = backend_config.backend_cls()
        self._scaling = scaling_config
        self._experiment_name = experiment_name
        self._trial_id = trial_id
        self._storage_path = storage_path
        self.worker_group: Optional[WorkerGroup] = None
        self._training_started = False

    def start(self):
        self.worker_group = WorkerGroup(
            num_workers=self._scaling.num_workers,
            resources_per_worker=self._scaling._resources_per_worker_not_none(),
            placement_strategy=self._scaling.placement_strategy,
        )
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(
        self,
        train_fn: Callable,
        config: Optional[Dict[str, Any]],
        checkpoint: Optional[Checkpoint] = None,
        datasets: Optional[Dict[str, Any]] = None,
    ):
        assert self.worker_group is not None, "call start() first"
        self._backend.on_training_start(self.worker_group, self._backend_config)
        n = len(self.worker_group)
        shards = _split_datasets(datasets, n)
        refs = []
        for rank, worker in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_size=n,
                world_rank=rank,
                local_rank=rank,  # single-host group; node packing refines this
                local_world_size=n,
                experiment_name=self._experiment_name,
                trial_id=self._trial_id,
                mesh_shape=self._scaling.mesh_shape,
                storage_path=self._storage_path,
            )
            refs.append(
                worker.start_training.remote(
                    train_fn, config, ctx, checkpoint, shards[rank]
                )
            )
        rt.get(refs)
        self._training_started = True
        self._done = [False] * n

    def get_next_results(self) -> Optional[List[_TrainingResult]]:
        """One result per still-running worker; None once all finished.
        All workers report in lockstep (same number of report() calls),
        as the reference requires."""
        assert self._training_started
        wg = self.worker_group
        live = [i for i, d in enumerate(self._done) if not d]
        if not live:
            return None
        refs = [wg.workers[i].get_next_result.remote() for i in live]
        try:
            results: List[_TrainingResult] = rt.get(refs)
        except Exception as e:
            raise TrainingWorkerError(f"training worker died: {e}") from e
        out: List[_TrainingResult] = []
        for i, res in zip(live, results):
            if res.error is not None:
                raise TrainingWorkerError(
                    f"worker {i} failed: {res.error!r}\n"
                    + getattr(res.error, "_rt_traceback", "")
                ) from res.error
            if res.done:
                self._done[i] = True
            else:
                out.append(res)
        if not out and all(self._done):
            return None
        return out if out else self.get_next_results()

    def shutdown(self):
        if self.worker_group is not None:
            try:
                self._backend.on_shutdown(self.worker_group, self._backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
        self._training_started = False
