"""Checkpoint: a directory-of-files abstraction.

Reference: `train/_checkpoint.py` — a Checkpoint is a handle to a
directory (local path here; the reference adds pyarrow-fs URIs), with
`from_directory` / `to_directory` / `as_directory` and a metadata
sidecar.  Orbax/flax serialization composes on top: callers write arrays
into the directory however they like (`orbax`, `np.savez`, msgpack).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional

_METADATA_FILE = ".metadata.json"


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        self._temp_source = False

    # -- constructors --------------------------------------------------
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        if not os.path.isdir(path):
            raise ValueError(f"not a directory: {path}")
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        """Convenience for small state dicts (numpy-picklable)."""
        import pickle

        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        with open(os.path.join(d, "state.pkl"), "wb") as f:
            pickle.dump(data, f)
        ck = cls(d)
        ck._temp_source = True  # persist_checkpoint may reclaim the dir
        return ck

    def to_dict(self) -> Dict[str, Any]:
        from ray_tpu.core import serialization

        with open(os.path.join(self.path, "state.pkl"), "rb") as f:
            return serialization.loads(f.read())

    # -- directory access ----------------------------------------------
    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into `path` (or a temp dir)."""
        dest = path or tempfile.mkdtemp(prefix="rt_ckpt_")
        os.makedirs(dest, exist_ok=True)
        for entry in os.listdir(self.path):
            src = os.path.join(self.path, entry)
            dst = os.path.join(dest, entry)
            if os.path.isdir(src):
                shutil.copytree(src, dst, dirs_exist_ok=True)
            else:
                shutil.copy2(src, dst)
        return dest

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        """Zero-copy when local: yields the backing directory."""
        yield self.path

    # -- metadata ------------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        m = self.get_metadata()
        m.update(metadata)
        self.set_metadata(m)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and other.path == self.path


def _new_checkpoint_dirname(index: int) -> str:
    return f"checkpoint_{index:06d}"


def merge_into(checkpoint: Checkpoint, dest: str) -> str:
    """Merge one reported checkpoint's contents into `dest` (all
    reporting ranks land in the same directory — under DP every rank
    holds the same state; under model parallelism ranks write
    distinctly-named shard files).  Reclaims temp-sourced checkpoint
    directories after the copy."""
    os.makedirs(dest, exist_ok=True)
    checkpoint.to_directory(dest)
    if getattr(checkpoint, "_temp_source", False):
        shutil.rmtree(checkpoint.path, ignore_errors=True)
    return dest


def persist_checkpoint(checkpoint: Checkpoint, run_dir: str, index: int) -> str:
    """Copy a worker-local checkpoint into run storage (NON-atomic: the
    destination is visible while being written).  The trainer's fit
    loop uses `CheckpointManager.commit` instead, which stages all
    reporting ranks in a temp directory, records a per-file checksum
    manifest, and renames — a half-written "latest" is never trusted by
    the restore path.  Reference: `train/_internal/storage.py`
    persist_current_checkpoint."""
    return merge_into(
        checkpoint, os.path.join(run_dir, _new_checkpoint_dirname(index))
    )
