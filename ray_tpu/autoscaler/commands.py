"""Cluster launcher: `rt up / down / status <cluster.yaml>`.

Reference: `python/ray/autoscaler/_private/commands.py` (`ray up/down`)
+ the cluster YAML schema (`autoscaler/ray-schema.json`), collapsed to
the fields this framework's two providers need:

```yaml
cluster_name: my-tpu-cluster
provider:
  type: gcp_tpu            # or: local
  project: my-project
  zone: us-central2-b
  accelerator_type: v5e-8
  runtime_version: tpu-ubuntu2204-base
head:
  controller_host: 10.0.0.2  # head VM IP; REQUIRED to create workers
  controller_port: 7777      # where workers join
min_workers: 1
max_workers: 4
worker:
  accelerator_type: v5e-8
  num_workers: 4           # worker processes per node
```

`up` creates the head node then min_workers workers whose startup
script joins the head; `down` terminates every node carrying the
cluster label.  All API traffic goes through the provider's injectable
transport, so the whole flow dry-runs against a mock (tests) and the
CLI offers --dry-run for real configs.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.gcp import (
    _LIVE_STATES,
    GcpTpuNodeProvider,
    head_startup_script,
    worker_startup_script,
)


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    for key in ("cluster_name", "provider"):
        if key not in cfg:
            raise ValueError(f"cluster config missing required key {key!r}")
    ptype = cfg["provider"].get("type")
    if ptype not in ("gcp_tpu", "local"):
        raise ValueError(f"unknown provider type {ptype!r}")
    if ptype == "gcp_tpu":
        for key in ("project", "zone"):
            if key not in cfg["provider"]:
                raise ValueError(f"gcp_tpu provider needs {key!r}")
    return cfg


class _DryRunTransport:
    """Records the API calls `up/down` would make."""

    def __init__(self):
        self.calls: List[tuple] = []
        self.nodes: Dict[str, dict] = {}

    def __call__(self, method: str, url: str, body: Optional[dict]) -> dict:
        self.calls.append((method, url, body))
        if method == "POST":
            node_id = url.rsplit("nodeId=", 1)[-1]
            self.nodes[node_id] = {
                "name": url.split("?")[0] + "/" + node_id,
                "state": "READY",
                **(body or {}),
            }
        if method == "DELETE":
            self.nodes.pop(url.rsplit("/", 1)[-1], None)
        if method == "GET":
            return {"nodes": list(self.nodes.values())}
        return {}


def _provider_for(cfg: Dict[str, Any], transport=None) -> GcpTpuNodeProvider:
    p = cfg["provider"]
    head = cfg.get("head", {})
    controller_host = head.get("controller_host", "HEAD_IP")
    controller_port = int(head.get("controller_port", 7777))
    script = worker_startup_script(
        controller_host, controller_port,
        num_workers=int(cfg.get("worker", {}).get("num_workers", 0)),
    )
    return GcpTpuNodeProvider(
        project=p["project"],
        zone=p["zone"],
        cluster_name=cfg["cluster_name"],
        accelerator_type=p.get("accelerator_type", "v5e-8"),
        runtime_version=p.get("runtime_version", "tpu-ubuntu2204-base"),
        startup_script=script,
        network=p.get("network"),
        transport=transport,
    )


def up(cfg: Dict[str, Any], *, transport=None, _print=print) -> Dict[str, Any]:
    """Create head + min_workers workers.  Returns a summary dict."""
    provider = _provider_for(cfg, transport)
    # one list call: ids carry no type information, labels do
    nodes = provider._list()
    live = {
        n["name"].rsplit("/", 1)[-1]: n.get("labels", {}).get(
            "rt-node-type", "worker"
        )
        for n in nodes
        if n.get("state") in _LIVE_STATES
    }
    created: Dict[str, List[str]] = {"head": [], "worker": []}
    have_head = "head" in live.values()
    n_workers = int(cfg.get("min_workers", 0))
    if n_workers and not cfg.get("head", {}).get("controller_host"):
        raise ValueError(
            "head.controller_host is required to create workers: their "
            "startup script must point at the head's controller.  Run "
            "`up` with min_workers: 0 first, read the head VM's IP, set "
            "head.controller_host, then `up` again (or let the in-"
            "cluster autoscaler add workers)."
        )
    if not have_head:
        created["head"] = provider.create_node(
            {"node_type": "head",
             "accelerator_type": cfg.get("head", {}).get(
                 "accelerator_type",
                 cfg["provider"].get("accelerator_type", "v5e-8")),
             # the head bootstraps its own daemon (controller + noded
             # bound on all interfaces) instead of the worker script
             "startup_script": head_startup_script(
                 int(cfg.get("head", {}).get("controller_port", 7777)),
                 num_workers=int(cfg.get("head", {}).get(
                     "num_workers", 0)),
             )},
            1,
        )
        _print(f"created head node {created['head'][0]}")
    existing_workers = sum(1 for t in live.values() if t != "head")
    to_create = max(0, n_workers - existing_workers)
    if to_create:
        created["worker"] = provider.create_node(
            {"node_type": "worker",
             "accelerator_type": cfg.get("worker", {}).get(
                 "accelerator_type",
                 cfg["provider"].get("accelerator_type", "v5e-8"))},
            to_create,
        )
        _print(f"created {to_create} worker node(s)")
    return {"created": created, "live_before": sorted(live)}


def down(cfg: Dict[str, Any], *, transport=None, _print=print) -> List[str]:
    """Terminate every node of the cluster; returns their ids."""
    provider = _provider_for(cfg, transport)
    ids = provider.non_terminated_nodes()
    for pid in ids:
        provider.terminate_node(pid)
        _print(f"terminated {pid}")
    return ids


def status(cfg: Dict[str, Any], *, transport=None) -> List[Dict[str, Any]]:
    return _provider_for(cfg, transport).list_cluster_nodes()


# ----------------------------------------------------------------------
# attach / exec (reference: `ray attach` / `ray exec`,
# `autoscaler/_private/commands.py` + `command_runner.py`)
# ----------------------------------------------------------------------
def _head_runner(cfg: Dict[str, Any], *, transport=None,
                 runner_factory=None):
    """CommandRunner for the cluster's head node.  `runner_factory`
    (ip -> CommandRunner) is the injection seam tests use."""
    provider = _provider_for(cfg, transport)
    head_id = None
    for n in provider._list():
        if n.get("labels", {}).get("rt-node-type") == "head" and \
                n.get("state") in _LIVE_STATES:
            head_id = n["name"].rsplit("/", 1)[-1]
            break
    if head_id is None:
        raise RuntimeError(
            f"cluster {cfg['cluster_name']!r} has no live head node; "
            "run `rt up` first"
        )
    ip = provider.node_ip(head_id)
    if ip is None:
        raise RuntimeError(f"head node {head_id} reports no IP yet")
    if runner_factory is not None:
        return runner_factory(ip)
    from ray_tpu.autoscaler.command_runner import runner_for

    return runner_for(cfg, ip)


def exec_on_head(cfg: Dict[str, Any], command: str, *, transport=None,
                 runner_factory=None, timeout: Optional[float] = None):
    """Run one shell command on the head node; returns (rc, output)
    (reference: `ray exec`)."""
    runner = _head_runner(cfg, transport=transport,
                          runner_factory=runner_factory)
    return runner.run(command, timeout=timeout)


def attach(cfg: Dict[str, Any], *, transport=None, runner_factory=None,
           _print=print) -> int:
    """Interactive shell on the head node (reference: `ray attach`).
    Prints the equivalent ssh command first so the session is
    reproducible without the CLI."""
    runner = _head_runner(cfg, transport=transport,
                          runner_factory=runner_factory)
    _print("attaching: " + " ".join(runner.remote_shell_command("bash")))
    return runner.run_interactive("bash")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="rt-cluster", description=__doc__)
    p.add_argument("command",
                   choices=["up", "down", "status", "exec", "attach"])
    p.add_argument("config", help="cluster YAML path")
    p.add_argument("--dry-run", action="store_true",
                   help="print the API calls instead of making them")
    p.add_argument("--cmd", default=None,
                   help="shell command for `exec`")
    args = p.parse_args(argv)
    cfg = load_cluster_config(args.config)
    transport = _DryRunTransport() if args.dry_run else None
    if args.command in ("attach", "exec") and args.dry_run:
        # these commands run over ssh, not the provider API — there is
        # no call list to preview
        p.error(f"--dry-run is not supported with {args.command}")
    if args.command == "attach":
        return attach(cfg, transport=transport)
    if args.command == "exec":
        if not args.cmd:
            p.error("exec requires --cmd")
        rc, out = exec_on_head(cfg, args.cmd, transport=transport)
        print(out, end="")
        return rc
    fn = {"up": up, "down": down, "status": status}[args.command]
    out = fn(cfg, transport=transport)
    if args.dry_run:
        for method, url, _body in transport.calls:
            print(f"DRY-RUN {method} {url}")
    print(json.dumps(out, indent=2, default=str))
    return 0


if __name__ == "__main__":
    main()
