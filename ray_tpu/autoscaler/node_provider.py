"""Node providers: pluggable backends that create/terminate nodes.

Reference: `python/ray/autoscaler/node_provider.py` NodeProvider
interface; `LocalNodeProvider` plays the role of
`FakeMultiNodeProvider` (`_private/fake_multi_node/node_provider.py:236`,
`RAY_FAKE_CLUSTER=1`) — real node daemons as local processes, which is
also the single-host "cluster" story.  A cloud provider (GKE/TPU-VM)
implements the same three methods against its API.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Any, Dict, List, Optional


class NodeProvider:
    def create_node(self, node_config: Dict[str, Any], count: int = 1) -> List[str]:
        """Launch nodes; returns provider node ids."""
        raise NotImplementedError

    def create_slice(self, node_config: Dict[str, Any], hosts: int) -> List[str]:
        """Provision `hosts` ICI-connected hosts as ONE unit (a TPU
        slice).  Default: per-host creation — the v2 autoscaler rolls
        the whole set back on partial failure, giving all-or-nothing
        semantics on top.  Cloud providers that can allocate a slice in
        a single API call (one multi-host TPU VM node) override this.
        """
        out: List[str] = []
        try:
            for _ in range(hosts):
                out.extend(self.create_node(node_config, 1))
        except Exception:
            for pid in out:
                try:
                    self.terminate_node(pid)
                except Exception:
                    pass
            raise
        return out

    def terminate_node(self, provider_id: str):
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_is_ready(self, provider_id: str) -> bool:
        """True when the node has actually booted (GCP TPU state READY,
        GKE pod phase Running).  The v2 autoscaler gates REQUESTED ->
        RUNNING promotion on this for providers that cannot map provider
        ids to runtime nodes — without it a Pending pod/VM would be
        promoted on sight, disabling the slice ready-timeout reaper and
        double-launching slices while one is still booting.  Default
        True: providers whose listing already implies liveness."""
        return True

    def node_resources(self, provider_id: str) -> Dict[str, float]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Spawns real node daemons joined to an existing head."""

    def __init__(self, controller_addr, base_dir: Optional[str] = None):
        self._controller_addr = tuple(controller_addr)
        self._base = base_dir or os.path.join(
            os.environ.get("RT_TMPDIR", "/tmp/ray_tpu"),
            f"autoscaler_{os.getpid()}",
        )
        os.makedirs(self._base, exist_ok=True)
        self._nodes: Dict[str, Dict[str, Any]] = {}
        self._next = 0

    def create_node(self, node_config: Dict[str, Any], count: int = 1) -> List[str]:
        from ray_tpu.core.node_launcher import launch_noded

        out = []
        for _ in range(count):
            idx = self._next
            self._next += 1
            resources = dict(node_config.get("resources", {}))
            num_cpus = float(node_config.get("num_cpus", 4))
            proc, ready = launch_noded(
                os.path.join(self._base, f"node_{idx}"),
                controller_addr=self._controller_addr,
                num_cpus=num_cpus,
                resources=resources,
                labels=dict(node_config.get("labels", {})) or None,
                num_workers=int(node_config.get("num_workers", 2)),
            )
            pid = f"local-{idx}"
            self._nodes[pid] = {
                "proc": proc,
                "node_id": ready["node_id"],
                "resources": {"CPU": num_cpus, **resources},
                "launched_at": time.time(),
            }
            out.append(pid)
        return out

    def terminate_node(self, provider_id: str):
        info = self._nodes.pop(provider_id, None)
        if info is None:
            return
        proc = info["proc"]
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [
            pid for pid, info in self._nodes.items()
            if info["proc"].poll() is None
        ]

    def node_resources(self, provider_id: str) -> Dict[str, float]:
        return dict(self._nodes[provider_id]["resources"])

    def runtime_node_id(self, provider_id: str) -> str:
        return self._nodes[provider_id]["node_id"]
